#!/usr/bin/env python
"""Multi-replica serving router CLI: least-loaded, drain-aware dispatch.

Runs the repo's router (``deepspeed_tpu/serving/router.py``) as a
standalone HTTP front-end over N replica endpoints (each a
``init_serving(metrics_port=...)`` metrics server exposing ``/healthz`` +
``/statz`` + ``POST /generate``):

    python tools/router.py http://host:9101 http://host:9102
    python tools/router.py r0=host:9101 r1=host:9102   # named replicas
    python tools/router.py --port 9200 url...          # fixed front port
    python tools/router.py --selftest                  # synthetic 2-replica check

The router serves ``POST /generate`` (dispatched least-loaded with
session affinity and retry-elsewhere on drain/failure — no dropped
requests), ``GET /healthz`` (ready while ANY replica is), ``GET
/replicaz`` (membership + per-replica load view), and ``GET /statz``
(its own ``ds_router_*`` counters/gauges, scrapeable by
``tools/fleet_dump.py`` like any other endpoint).

``--selftest`` spins up two synthetic stdlib replicas and drives the
real Router through least-loaded picks, session affinity, a mid-trace
drain with redistribution, and the full HTTP front-end (wired as a
tier-1 unit test so this offline tool cannot silently rot).  Zero
dependencies beyond the repo's stdlib-only modules — **no jax import**
(asserted by the selftest), same contract as ``tools/fleet_dump.py``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _load_router_module():
    """``deepspeed_tpu/serving/router.py`` WITHOUT importing the package
    (no jax on an operator box): reuse the module when the package is
    already loaded (in-process tests), else exec it by file path."""
    mod = sys.modules.get("deepspeed_tpu.serving.router")
    if mod is not None:
        return mod
    mod = sys.modules.get("_ds_router")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "serving", "router.py")
    spec = importlib.util.spec_from_file_location("_ds_router", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_router"] = mod
    spec.loader.exec_module(mod)
    return mod


_router = _load_router_module()
Router = _router.Router
RouterServer = _router.RouterServer


# ---------------------------------------------------------------------------
# selftest (synthetic replicas; tier-1 wired)
# ---------------------------------------------------------------------------


class _FakeReplica:
    """Stdlib stand-in for a ServingEngine replica: settable readiness
    and load gauges, and a deterministic ``/generate`` (tokens are a pure
    function of the prompt, so 'token-identical across replicas' is
    checkable without any model)."""

    def __init__(self, name: str):
        self.name = name
        self.ready = True
        self.reason = None
        self.queue_depth = 0
        self.active_slots = 0
        self.served: List[int] = []      # request ids this replica served
        self.requeue_next = 0            # N next /generate calls -> 503
        self.error_next = 0              # N next /generate calls -> 500
        self.shed_next = 0               # N next /generate calls -> 429
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.partition("?")[0]
                if path == "/healthz":
                    if fake.ready:
                        self._send(200, {"ready": True})
                    else:
                        self._send(503, {"ready": False,
                                         "reason": fake.reason or "draining"})
                elif path == "/statz":
                    self._send(200, {"enabled": True, "metrics": {
                        "ds_serve_queue_depth": fake.queue_depth,
                        "ds_serve_active_slots": fake.active_slots,
                        "ds_serve_kv_pages_used": 0,
                        "ds_serve_kv_pages_free": 8}})
                else:
                    self.send_error(404)

            def do_POST(self):
                if self.path.partition("?")[0] != "/generate":
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not fake.ready:
                    self._send(503, {"error": "draining"})
                    return
                if fake.requeue_next > 0:
                    fake.requeue_next -= 1
                    self._send(503, {"error": "request requeued: replica "
                                              "draining", "requeued": True})
                    return
                if fake.error_next > 0:
                    fake.error_next -= 1
                    self._send(500, {"error": "injected 500"})
                    return
                if fake.shed_next > 0:
                    fake.shed_next -= 1
                    self._send(429, {"error": "admission queue full",
                                     "shed": True, "retry_after_s": 0.2})
                    return
                prompt = payload.get("prompt") or []
                max_new = int(payload.get("max_new_tokens", 4))
                fake.served.append(int(payload.get("rid", -1)))
                self._send(200, {"tokens": _fake_tokens(prompt, max_new),
                                 "finish_reason": "length"})

            def log_message(self, fmt, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _fake_tokens(prompt: List[int], max_new: int) -> List[int]:
    seed = sum(int(t) for t in prompt) % 997
    return [(seed + i) % 997 for i in range(max_new)]


def selftest() -> int:
    if os.path.basename(sys.argv[0]).startswith("router"):
        # standalone contract: this tool must never drag jax in
        assert "jax" not in sys.modules, "tools/router.py imported jax"
    reps = [_FakeReplica("a"), _FakeReplica("b")]
    a, b = reps
    # a private enabled registry: the selftest must not flip the
    # process-global one (in-process tier-1 runs share it)
    reg = _router._metrics.MetricsRegistry().enable()
    router = Router([f"a={a.url}", f"b={b.url}"], dispatch_rounds=4,
                    retry_backoff=0.01, registry=reg)
    try:
        # membership: both come up ready on the first poll
        router.refresh()
        assert [r.ready for r in router.replicas] == [True, True]
        # least-loaded: load up a -> picks land on b
        a.queue_depth, b.queue_depth = 6, 0
        router.refresh()
        code, body = router.dispatch({"prompt": [1, 2, 3],
                                      "max_new_tokens": 4})
        assert code == 200 and body["replica"] == "b", body
        assert body["tokens"] == _fake_tokens([1, 2, 3], 4)
        # session affinity: pin a session to the (now) least-loaded a,
        # then make a look MORE loaded — the session sticks anyway
        # (prefix-cache locality beats a small load delta)
        a.queue_depth = 0
        router.refresh()
        code, body = router.dispatch({"prompt": [7], "max_new_tokens": 2,
                                      "session": "chat-1"})
        assert code == 200 and body["replica"] == "a", body
        a.queue_depth = 50
        router.refresh()
        code, body = router.dispatch({"prompt": [7, 8], "max_new_tokens": 2,
                                      "session": "chat-1"})
        assert code == 200 and body["replica"] == "a", body
        # drain redistribution: a flips not-ready -> the session MOVES,
        # nothing is dropped
        a.ready = True               # healthz still 200 (drain just hit):
        a.requeue_next = 1           # /generate hands the request back
        code, body = router.dispatch({"prompt": [7, 8, 9],
                                      "max_new_tokens": 2,
                                      "session": "chat-1"})
        assert code == 200 and body["replica"] == "b", body
        retries = router.registry.get("ds_router_retries_total")
        assert retries is not None and retries.value >= 1
        # a now fully draining (healthz 503): excluded from membership,
        # a full trace completes on b alone — zero dropped
        a.ready, a.reason = False, "draining"
        router.refresh()
        assert router.pick() is not None
        results = []
        for i in range(6):
            code, body = router.dispatch({"prompt": [i, i + 1],
                                          "max_new_tokens": 3, "rid": i})
            results.append((code, body))
        assert all(c == 200 for c, _ in results), results
        assert all(bd["replica"] == "b" for _, bd in results)
        assert all(bd["tokens"] == _fake_tokens([i, i + 1], 3)
                   for i, (_, bd) in enumerate(results))
        # dispatch accounting: per-replica counters moved
        da = router.registry.get("ds_router_dispatch_total",
                                 labels={"replica": "a"})
        db = router.registry.get("ds_router_dispatch_total",
                                 labels={"replica": "b"})
        assert da.value >= 2 and db.value >= 8, (da.value, db.value)
        # the HTTP front-end end-to-end: /generate routed, /healthz ready,
        # /replicaz shows the drained member
        front = RouterServer(router).start()
        try:
            import urllib.request

            req = urllib.request.Request(
                front.url + "/generate",
                data=json.dumps({"prompt": [5, 5],
                                 "max_new_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.load(resp)
            assert out["tokens"] == _fake_tokens([5, 5], 2)
            with urllib.request.urlopen(front.url + "/healthz",
                                        timeout=5) as resp:
                assert json.load(resp)["ready"] is True
            with urllib.request.urlopen(front.url + "/replicaz",
                                        timeout=5) as resp:
                snap = json.load(resp)
            assert snap["ready"] == 1
            drained = [r for r in snap["replicas"] if r["name"] == "a"][0]
            assert not drained["ready"]
            with urllib.request.urlopen(front.url + "/statz",
                                        timeout=5) as resp:
                statz = json.load(resp)
            assert "ds_router_retries_total" in statz["metrics"]
        finally:
            front.stop()
        # every replica's /healthz back up -> membership heals
        a.ready = True
        router.refresh()
        assert sum(r.ready for r in router.replicas) == 2
    finally:
        for r in reps:
            r.stop()
    print("router selftest: OK (least-loaded, affinity, drain "
          "redistribution with zero drops, HTTP front-end)")
    return 0


# ---------------------------------------------------------------------------


def main(argv: List[str]) -> int:
    # flags take '--port=9200' or '--port 9200'; everything else is a
    # replica URL
    args: List[str] = []
    flags: Dict[str, str] = {}
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if not a.startswith("--"):
            args.append(a)
            continue
        name, sep, val = a.partition("=")
        if not sep and name == "--port" and rest:
            val = rest.pop(0)
        flags[name] = val
    if "--selftest" in flags:
        return selftest()
    if not args or "--help" in flags or "-h" in argv[1:]:
        print(__doc__.strip())
        return 0 if args else 2
    port = int(flags.get("--port") or 0)
    router = Router(args)
    router.registry.enable()
    router.start()
    server = RouterServer(router, port=port).start()
    ready = sum(r.ready for r in router.replicas)
    print(f"router: {server.url}/generate over {len(router.replicas)} "
          f"replica(s) ({ready} ready); /healthz /replicaz /statz")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
