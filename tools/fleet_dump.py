#!/usr/bin/env python
"""Fleet-scrape aggregation: merge N ``/statz`` endpoints into one view.

The multi-replica router (ROADMAP item 3) dispatches off each replica's
live ``ds_serve_*`` gauges; this tool is that signal surface delivered as
an operator view — scrape every replica, merge the series, and show the
per-replica SKEW (a hot replica reads directly off the skew column):

    python tools/fleet_dump.py http://host:9101 http://host:9102
    python tools/fleet_dump.py r1=host:9101 r2=host:9102   # named replicas
    python tools/fleet_dump.py --json url...               # machine-readable
    python tools/fleet_dump.py snap1.json snap2.json       # saved snapshots
    python tools/fleet_dump.py --supervisor-status=sup.json url...
    python tools/fleet_dump.py --supervisor-status=sup.json  # status alone
    python tools/fleet_dump.py --trace router=u0 ra=u1 rb=u2 --out=m.json
    python tools/fleet_dump.py --profiles ra=u1 rb=u2      # straggler view
    python tools/fleet_dump.py --selftest                  # parser self-check

``--trace`` switches to DISTRIBUTED-TRACE merge (docs/OBSERVABILITY.md
"Distributed tracing"): every source is scraped at
``/requestz?format=perfetto`` (append ``#train`` to a URL for a training
process's step timeline; a non-URL source is read as a saved export
file), and the per-process Perfetto documents are merged into ONE
session on the FIRST source's clock.  Each export self-describes its
clock via ``otherData.clock_anchor_unix`` (the wall time its timestamp
origin corresponds to — the ``set_trace_clock_anchor()`` contract), so
translation is a pure shift: ``ts += (anchor_unix_src -
anchor_unix_ref) * 1e6``.  Pids are remapped per source and process
names prefixed ``<source>:`` so N processes cannot collide.
``--capture=<source>=<file>`` merges a ``/profilez`` device capture
(plain or ``.gz`` trace-event JSON) on the named source's clock — its
timestamps share that process's trace-session domain.  Every scrape and
status output also carries a ``scraped_at`` ``{wall, mono}`` pair so a
metrics view, a supervisor status, and a trace can be correlated in
time; the rendered views show the resulting skew.

``--profiles`` merges N replicas' CONTINUOUS-PROFILER histories
(docs/OBSERVABILITY.md "Continuous profiling"): every source is scraped
at ``/profilez/history`` (a non-URL source is a saved snapshot, a single
window file, or a ``profile_history/`` ring directory), each window is
placed on the FIRST source's unix clock via its ``clock`` anchors (the
same anchor-shift contract as ``--trace``), and the view shows each
replica's latest window plus the per-replica DEVICE-BUSY SKEW — a
replica whose device-busy ratio trails the fleet is the straggler.

``--supervisor-status=<file>`` renders a supervisor's ``--status-file``
JSON (either ``train_supervisor`` or ``serve_supervisor`` schema:
ladder counters, replica/child states, restart timestamps) above the
scrape table — and works with no ``/statz`` sources at all, because a
down fleet has nothing to scrape but the status file survives.

Merge semantics by instrument kind (fetched from ``/statz?kinds=1``; a
saved snapshot without kinds falls back to the ``*_total`` naming
heuristic):

- **counters** sum across replicas (fleet totals: requests, tokens);
- **gauges** report the MEAN as the fleet value plus min/max spread
  (fleet state: queue depth, active slots, KV pages — the per-replica
  columns carry the absolute values, ``skew`` the imbalance);
- **histograms** merge exactly: bucket counts add element-wise (every
  replica uses the same fixed bounds), so the FLEET p50/p99 is computed
  from the merged distribution, not averaged from per-replica quantiles
  (averaging quantiles is wrong; merging counts is not).  When the bucket
  layout is not one this repo ships (34 log buckets / 17 linear ratio
  buckets), merged quantiles are omitted and per-replica p99s remain.

``skew`` is ``(max - min) / mean`` over the per-replica values (counters:
their deltas-as-values; histograms: per-replica p99) — ``0`` means a
balanced fleet, ``>= 1`` means some replica sees a multiple of another's
load, which is exactly the router's rebalance trigger.

``--selftest`` builds two synthetic replicas through the real
``MetricsRegistry`` and asserts the merge (wired as a tier-1 unit test so
this offline tool cannot silently rot).  Zero dependencies beyond the
repo's stdlib-only metrics module — no jax import.
"""

from __future__ import annotations

import gzip
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from metrics_dump import (base_url, is_url,  # noqa: E402
                          load_profile_history, render_table)


def _load_metrics():
    """The repo's stdlib-only metrics module WITHOUT importing the
    ``deepspeed_tpu`` package (whose ``__init__`` pulls in jax — an
    operator box scraping a fleet has no jax): reuse the module when the
    package is already loaded (tests), else exec ``metrics.py`` by file
    path."""
    mod = sys.modules.get("deepspeed_tpu.monitor.metrics")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "monitor", "metrics.py")
    spec = importlib.util.spec_from_file_location("_ds_fleet_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_metrics = _load_metrics()
DEFAULT_BUCKETS = _metrics.DEFAULT_BUCKETS
_quantile_from_counts = _metrics._quantile_from_counts

# bucket bounds inferable from snapshot bucket-list length: the repo's two
# fixed layouts (DEFAULT log buckets; 16-linear ratio histograms)
_RATIO_BUCKETS = tuple(i / 16 for i in range(1, 17))
_BOUNDS_BY_LEN = {len(DEFAULT_BUCKETS) + 1: DEFAULT_BUCKETS,
                  len(_RATIO_BUCKETS) + 1: _RATIO_BUCKETS}


def _stamp_now() -> Dict[str, float]:
    """The correlation stamp every output carries: wall time (cross-
    process comparable) paired with this process's monotonic clock
    (interval-true locally) — the pair lets a scrape, a supervisor
    status, and a trace session be lined up in time."""
    return {"wall": time.time(), "mono": time.monotonic()}


def fetch_statz(url: str, timeout: float = 5.0) -> Dict[str, object]:
    """GET one replica's ``/statz?kinds=1`` (URL normalized via
    metrics_dump's shared helper); returns the parsed body
    ``{"metrics", "kinds"?}``."""
    import urllib.request

    with urllib.request.urlopen(base_url(url) + "/statz?kinds=1",
                                timeout=timeout) as resp:
        return json.load(resp)


def load_source(src: str) -> Dict[str, object]:
    """A live endpoint or a saved ``/statz`` snapshot file."""
    if is_url(src):
        return fetch_statz(src)
    with open(src) as fh:
        data = json.load(fh)
    if "metrics" not in data:          # bare metrics mapping
        data = {"metrics": data}
    return data


def _kind_of(name: str, values: List[object],
             kinds: Dict[str, str]) -> str:
    k = kinds.get(name)
    if k:
        return k
    if any(isinstance(v, dict) and "buckets" in v for v in values):
        return "histogram"
    return "counter" if name.endswith("_total") else "gauge"


def _spread(vals: List[float]) -> Dict[str, float]:
    mean = sum(vals) / len(vals)
    lo, hi = min(vals), max(vals)
    return {"min": lo, "max": hi, "mean": mean,
            "skew": ((hi - lo) / abs(mean)) if mean else 0.0}


def _merge_histograms(per: Dict[str, dict]) -> Dict[str, object]:
    counts = [v["count"] for v in per.values()]
    sums = [v["sum"] for v in per.values()]
    total = sum(counts)
    out: Dict[str, object] = {
        "count": total, "sum": sum(sums),
        "mean": (sum(sums) / total) if total else 0.0,
        "per_replica": {r: {"count": v["count"], "p99": v["p99"]}
                        for r, v in per.items()},
    }
    p99s = [v["p99"] for v in per.values() if v["count"]]
    if len(p99s) >= 2:
        out["p99_skew"] = _spread(p99s)["skew"]
    # exact merged quantiles when the bucket layout is one we know: the
    # element-wise count sum IS the fleet distribution
    lens = {len(v.get("buckets", [])) for v in per.values()}
    if len(lens) == 1:
        bounds = _BOUNDS_BY_LEN.get(lens.pop())
        if bounds is not None and total:
            merged = [0] * (len(bounds) + 1)
            for v in per.values():
                for i, c in enumerate(v["buckets"]):
                    merged[i] += c
            out["p50"] = _quantile_from_counts(bounds, merged, 0.5)
            out["p99"] = _quantile_from_counts(bounds, merged, 0.99)
    return out


def merge_snapshots(snaps: Dict[str, Dict[str, object]],
                    kinds: Optional[Dict[str, str]] = None
                    ) -> Dict[str, object]:
    """Merge ``{replica: metrics-mapping}`` into the fleet view
    ``{name: entry}`` (labeled families nest one entry per label set)."""
    kinds = kinds or {}
    names: Dict[str, None] = {}
    for m in snaps.values():
        for n in m:
            names.setdefault(n)
    fleet: Dict[str, object] = {}
    for name in names:
        per = {r: m[name] for r, m in snaps.items() if name in m}
        vals = list(per.values())
        # a labeled family ({'{reason="eos"}': ...}): recurse per label
        if all(isinstance(v, dict) and
               all(k.startswith("{") for k in v) for v in vals):
            labels: Dict[str, None] = {}
            for v in vals:
                for ls in v:
                    labels.setdefault(ls)
            fam = {}
            for ls in labels:
                sub = {r: {name: v[ls]} for r, v in per.items() if ls in v}
                fam[ls] = merge_snapshots(sub, kinds)[name]
            fleet[name] = fam
            continue
        kind = _kind_of(name, vals, kinds)
        if kind == "histogram":
            hist = {r: v for r, v in per.items() if isinstance(v, dict)}
            if hist:
                fleet[name] = {"kind": "histogram",
                               **_merge_histograms(hist)}
            continue
        nums = {r: float(v) for r, v in per.items()
                if isinstance(v, (int, float))}
        if not nums:
            continue
        entry = {"kind": kind, "per_replica": nums,
                 **_spread(list(nums.values()))}
        entry["sum" if kind == "counter" else "value"] = (
            sum(nums.values()) if kind == "counter"
            else entry["mean"])
        fleet[name] = entry
    return fleet


# ---------------------------------------------------------------------------
# distributed-trace merge (--trace): N /requestz perfetto exports + device
# captures onto the first source's clock
# ---------------------------------------------------------------------------


def fetch_trace(url: str, kind: str = "",
                timeout: float = 5.0) -> Dict[str, object]:
    """GET one process's ``/requestz?format=perfetto`` export (router
    hops, a replica's request spans, or — with ``kind='train'`` — the
    training step timeline)."""
    import urllib.request

    q = "/requestz?format=perfetto" + (f"&kind={kind}" if kind else "")
    with urllib.request.urlopen(base_url(url) + q, timeout=timeout) as resp:
        return json.load(resp)


def load_capture(path: str) -> Dict[str, object]:
    """A ``/profilez`` device capture: trace-event JSON, plain or
    gzipped, either the full document or a bare event list."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as fh:
        data = json.load(fh)
    if isinstance(data, list):
        data = {"traceEvents": data}
    return data


def _shift_events(events: List[dict], shift_us: float, pid_base: int,
                  src: str) -> List[dict]:
    """One source's events onto the merged session: timestamps shifted
    into the reference clock, pids offset into the source's own block,
    process names prefixed with the source name."""
    out = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        ev = dict(ev)
        if "pid" in ev:
            try:
                ev["pid"] = pid_base + int(ev["pid"])
            except (TypeError, ValueError):
                ev["pid"] = pid_base
        if isinstance(ev.get("ts"), (int, float)):
            ev["ts"] = ev["ts"] + shift_us
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            args = dict(ev.get("args") or {})
            args["name"] = f"{src}:{args.get('name', '')}"
            ev["args"] = args
        out.append(ev)
    return out


def merge_traces(docs: Dict[str, Dict[str, object]],
                 captures: Optional[Dict[str, List[dict]]] = None
                 ) -> Dict[str, object]:
    """Merge per-process Perfetto exports into ONE session on the FIRST
    source's clock.

    Anchor-translation contract (docs/OBSERVABILITY.md): each export's
    timestamps are microseconds since its process's clock anchor, and
    ``otherData.clock_anchor_unix`` is the wall time of that origin —
    so source i's events land on the reference clock via ``ts +=
    (unix_i - unix_ref) * 1e6``.  A device capture under ``captures``
    shares its named source's trace-session clock and gets the same
    shift.  ``otherData.sources`` records every anchor and its applied
    shift (the cross-process skew, made visible instead of absorbed)."""
    if not docs:
        raise ValueError("--trace needs at least one source")
    captures = captures or {}
    names = list(docs)
    ref = names[0]
    ref_unix = float(
        (docs[ref].get("otherData") or {}).get("clock_anchor_unix") or 0.0)
    events: List[dict] = []
    sources: Dict[str, dict] = {}
    pid_base = 0
    shifts: Dict[str, float] = {}
    for name in names:
        doc = docs[name]
        other = doc.get("otherData") or {}
        unix = float(other.get("clock_anchor_unix") or ref_unix)
        shift = (unix - ref_unix) * 1e6
        shifts[name] = shift
        pid_base += 1000
        sources[name] = {"clock_anchor_unix": unix,
                         "clock_source": other.get("clock_source"),
                         "shift_us": round(shift, 3),
                         "pid_base": pid_base}
        events.extend(_shift_events(
            list(doc.get("traceEvents") or []), shift, pid_base, name))
    for name, caps in captures.items():
        if name not in shifts:
            raise ValueError(
                f"--capture={name}=... names no --trace source "
                f"(have: {', '.join(names)})")
        for j, cap in enumerate(caps):
            pid_base += 1000
            events.extend(_shift_events(
                list(cap.get("traceEvents") or []), shifts[name],
                pid_base, f"{name}:device{j if len(caps) > 1 else ''}"))
    return {"displayTimeUnit": "ns", "traceEvents": events,
            "otherData": {"reference": ref,
                          "clock_anchor_unix": ref_unix,
                          "scraped_at": _stamp_now(),
                          "sources": sources,
                          "domain": "microseconds since the reference "
                                    "source's clock anchor"}}


# ---------------------------------------------------------------------------
# continuous-profiler history merge (--profiles): N /profilez/history
# snapshots onto the first source's unix clock + device-busy skew
# ---------------------------------------------------------------------------


def merge_profiles(histories: Dict[str, Dict[str, object]]
                   ) -> Dict[str, object]:
    """Merge ``{replica: /profilez/history snapshot}`` onto ONE clock.

    Each window record carries its capture's ``clock`` anchors
    (``window_unix_lo``/``window_unix_hi`` — wall time of the window's
    span, the ``set_trace_clock_anchor()`` contract), so placement on the
    first source's clock is the same pure shift as ``--trace``:
    ``offset_s = window_unix_lo - ref_lo``.  The straggler signal is the
    spread of the LATEST windows' device-busy ratios: a replica whose
    device sits idle while its peers are busy reads directly off the
    skew."""
    if not histories:
        raise ValueError("--profiles needs at least one source")
    timeline: List[Dict[str, object]] = []
    latest: Dict[str, Dict[str, object]] = {}
    for name, snap in histories.items():
        for w in snap.get("windows") or []:
            rec = dict(w)
            rec["replica"] = name
            timeline.append(rec)
            cur = latest.get(name)
            if cur is None or (rec.get("seq") or 0) >= (cur.get("seq") or 0):
                latest[name] = rec
    ref = next(iter(histories))
    ref_lo = None
    for w in timeline:
        if w["replica"] == ref:
            lo = (w.get("clock") or {}).get("window_unix_lo")
            if lo and (ref_lo is None or lo < ref_lo):
                ref_lo = float(lo)
    for w in timeline:
        lo = (w.get("clock") or {}).get("window_unix_lo")
        w["offset_s"] = (round(float(lo) - ref_lo, 6)
                         if lo and ref_lo is not None else None)
    timeline.sort(key=lambda w: (w.get("offset_s")
                                 if w.get("offset_s") is not None else 0.0,
                                 str(w["replica"])))
    out: Dict[str, object] = {"reference": ref,
                              "reference_unix_lo": ref_lo,
                              "replicas": sorted(histories),
                              "scraped_at": _stamp_now(),
                              "windows": timeline,
                              "latest": latest}
    busy = [float(w.get("busy_ratio") or 0.0) for w in latest.values()]
    if busy:
        out["device_busy"] = _spread(busy)
    return out


def render_profiles(merged: Dict[str, object]) -> str:
    latest = merged.get("latest") or {}
    if not latest:
        return ("(no continuous-profiler windows on any replica — is "
                "continuous_profiler.enabled set?)")
    rows = []
    for name in sorted(latest):
        w = latest[name]
        off = w.get("offset_s")
        rows.append([
            name, str(w.get("engine", "")), str(w.get("seq", "")),
            str(w.get("step", "")),
            f"{float(w.get('window_s') or 0.0) * 1e3:.3f}",
            f"{100 * float(w.get('busy_ratio') or 0.0):.2f}%",
            f"{100 * float(w.get('coverage_ratio') or 0.0):.2f}%",
            f"{100 * float(w.get('overhead_ratio') or 0.0):.2f}%",
            _fmt(off) if off is not None else ""])
    lines = [f"profiles: {len(merged.get('windows') or [])} window(s) "
             f"from {len(latest)} replica(s), clock reference "
             f"{merged.get('reference')}"]
    lines += render_table(["replica", "engine", "seq", "step", "wall_ms",
                           "busy", "coverage", "overhead", "offset_s"],
                          rows)
    busy = merged.get("device_busy")
    if isinstance(busy, dict):
        lines.append(f"device busy: min {100 * busy['min']:.2f}%  "
                     f"max {100 * busy['max']:.2f}%  "
                     f"mean {100 * busy['mean']:.2f}%  "
                     f"skew {busy['skew']:.4g}"
                     + ("  <- straggler signal" if busy["skew"] > 0.2
                        else ""))
    return "\n".join(lines)


def profiles_main(args: List[str], flags: set) -> int:
    """``--profiles``: scrape/load every source's continuous-profiler
    history and render the merged straggler view (``--json`` for the
    machine-readable merge)."""
    histories: Dict[str, Dict[str, object]] = {}
    for i, src in enumerate(args):
        name, sep, rest = src.partition("=")
        if sep and not name.startswith("http") and "/" not in name:
            src = rest
        else:
            name = f"r{i}"
        histories[name] = load_profile_history(src)
    try:
        merged = merge_profiles(histories)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if "--json" in flags:
        print(json.dumps(merged, sort_keys=True, default=str))
    else:
        print(render_profiles(merged))
    return 0


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def fleet_rows(fleet: Dict[str, object],
               replicas: List[str]) -> List[List[str]]:
    """[metric, fleet, p50, p99, <one col per replica>, skew] rows."""
    rows = []

    def emit(name, e):
        if isinstance(e, dict) and "kind" not in e:      # labeled family
            for ls, sub in sorted(e.items()):
                emit(f"{name}{ls}", sub)
            return
        if e["kind"] == "histogram":
            per = e["per_replica"]
            rows.append([name, f"n={e['count']}",
                         _fmt(e["p50"]) if "p50" in e else "",
                         _fmt(e["p99"]) if "p99" in e else ""]
                        + [(_fmt(per[r]["p99"]) if r in per and
                            per[r]["count"] else "") for r in replicas]
                        + [_fmt(e["p99_skew"]) if "p99_skew" in e else ""])
            return
        per = e["per_replica"]
        head = _fmt(e["sum"]) if e["kind"] == "counter" else _fmt(e["value"])
        rows.append([name, head, "", ""]
                    + [(_fmt(per[r]) if r in per else "") for r in replicas]
                    + [_fmt(round(e["skew"], 4))])

    for name, e in sorted(fleet.items()):
        emit(name, e)
    return rows


def render(fleet: Dict[str, object], replicas: List[str]) -> str:
    header = (["metric", "fleet", "p50", "p99"] + list(replicas) + ["skew"])
    return "\n".join(render_table(header, fleet_rows(fleet, replicas)))


def render_supervisor_status(st: Dict[str, object]) -> str:
    """Render a supervisor ``--status-file`` JSON (either supervisor's
    schema — ``tools/{train,serve}_supervisor.py --status-file``):
    supervisor truth next to the scraped metrics, no log scraping."""
    kind = st.get("kind", "supervisor")
    head = (f"{kind}: state={st.get('state')} pid={st.get('pid')} "
            f"updated_unix={st.get('updated_unix')}")
    sc = st.get("scraped_at")
    if isinstance(sc, dict) and "wall" in sc:
        head += f" scraped_at={sc['wall']:.3f}"
        # the status-vs-scrape skew made visible: how stale the
        # supervisor's truth was at the moment this view was taken
        try:
            head += f" (age {sc['wall'] - float(st['updated_unix']):.1f}s)"
        except (KeyError, TypeError, ValueError):
            pass
    rows: List[List[str]] = []
    if "replicas" in st:                 # serve_supervisor: one row each
        for r in st["replicas"]:
            lad = r.get("ladder") or {}
            rows.append([str(r.get("index")), str(r.get("state")),
                         str(r.get("port", "")),
                         str(lad.get("crash_restarts", "")),
                         str(lad.get("preempt_restarts", "")),
                         f"{lad.get('restarts', '')}/"
                         f"{lad.get('max_restarts', '')}"])
        table = render_table(["replica", "state", "port", "crashes",
                              "preempts", "restarts"], rows)
    else:                                # train_supervisor: one child
        lad = st.get("ladder") or {}
        rows.append([str(st.get("incarnation")), str(st.get("state")),
                     str(st.get("child_pid", "")),
                     str(lad.get("crash_restarts", "")),
                     str(lad.get("preempt_restarts", "")),
                     f"{lad.get('restarts', '')}/"
                     f"{lad.get('max_restarts', '')}"])
        table = render_table(["incarnation", "state", "child_pid",
                              "crashes", "preempts", "restarts"], rows)
    return "\n".join([head] + list(table))


# ---------------------------------------------------------------------------
# selftest (bundled synthetic fixture; tier-1 wired)
# ---------------------------------------------------------------------------


def _synthetic_replicas() -> Tuple[Dict[str, dict], Dict[str, str]]:
    """Two synthetic replicas built through the REAL registry (so the
    fixture tracks the snapshot shape instead of freezing a copy of it)."""
    MetricsRegistry = _metrics.MetricsRegistry

    snaps, kinds = {}, {}
    for r, (reqs, depth, lats) in (
            ("r0", (100, 2, [0.01] * 90 + [0.5] * 10)),
            ("r1", (300, 8, [0.02] * 80 + [2.0] * 20))):
        reg = MetricsRegistry().enable()
        reg.counter("ds_serve_submitted_total").inc(reqs)
        reg.gauge("ds_serve_queue_depth").set(depth)
        h = reg.histogram("ds_serve_request_latency_seconds")
        for v in lats:
            h.record(v)
        reg.counter("ds_serve_finished_total",
                    labels={"reason": "eos"}).inc(reqs - 1)
        snaps[r] = reg.snapshot()
        kinds = {name: kind for (name, _), (kind, _) in
                 reg.typed_snapshot().items()}
    return snaps, kinds


def selftest() -> int:
    snaps, kinds = _synthetic_replicas()
    fleet = merge_snapshots(snaps, kinds)
    sub = fleet["ds_serve_submitted_total"]
    assert sub["kind"] == "counter" and sub["sum"] == 400, sub
    assert sub["per_replica"] == {"r0": 100.0, "r1": 300.0}
    assert sub["skew"] == (300 - 100) / 200
    q = fleet["ds_serve_queue_depth"]
    assert q["kind"] == "gauge" and q["min"] == 2 and q["max"] == 8
    lat = fleet["ds_serve_request_latency_seconds"]
    assert lat["count"] == 200
    # merged-distribution p99 lands in the slow replica's 2.0s log bucket
    # (upper bound ~3.16s) — per-replica p99s alone could never say that
    assert 1.0 < lat["p99"] <= 3.2, lat
    assert lat["p99_skew"] > 0
    fam = fleet["ds_serve_finished_total"]['{reason="eos"}']
    assert fam["sum"] == 99 + 299
    table = render(fleet, sorted(snaps))
    assert "ds_serve_submitted_total" in table and "400" in table
    print(table)
    # supervisor-status render: both schemas through one code path, with
    # the scraped_at pair rendered as status-vs-scrape age
    train_st = {"kind": "train_supervisor", "state": "backoff", "pid": 7,
                "incarnation": 2, "child_pid": 11, "updated_unix": 100.0,
                "scraped_at": {"wall": 103.5, "mono": 5.0},
                "ladder": {"restarts": 2, "max_restarts": 5,
                           "crash_restarts": 2, "preempt_restarts": 0}}
    out = render_supervisor_status(train_st)
    assert "train_supervisor: state=backoff" in out and "2/5" in out
    assert "scraped_at=103.500" in out and "age 3.5s" in out, out
    serve_st = {"kind": "serve_supervisor", "state": "running", "pid": 8,
                "target": 2, "replicas": [
                    {"index": 0, "state": "RUNNING", "port": 9101,
                     "ladder": {"restarts": 1, "max_restarts": 5,
                                "crash_restarts": 1,
                                "preempt_restarts": 0}},
                    {"index": 1, "state": "FAILED", "port": 9102,
                     "ladder": {"restarts": 5, "max_restarts": 5,
                                "crash_restarts": 5,
                                "preempt_restarts": 0}}]}
    out = render_supervisor_status(serve_st)
    assert "serve_supervisor: state=running" in out
    assert "FAILED" in out and "5/5" in out
    # trace merge: two exports whose anchors disagree by exactly 2s —
    # after translation the same wall instant must land on the same ts
    docs = {
        "router": {"traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "ds_router"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 1000.0, "dur": 500.0,
             "name": "dispatch (200)", "args": {"trace": "t" * 32}}],
            "otherData": {"clock_anchor_unix": 1000.0,
                          "clock_source": "router_process"}},
        "ra": {"traceEvents": [
            {"ph": "X", "pid": 1, "tid": 7, "ts": 0.0, "dur": 200.0,
             "name": "decode", "args": {"trace": "t" * 32}}],
            "otherData": {"clock_anchor_unix": 1002.0,
                          "clock_source": "process"}},
    }
    cap = {"traceEvents": [{"ph": "X", "pid": 3, "tid": 1, "ts": 50.0,
                            "dur": 10.0, "name": "fusion"}]}
    merged = merge_traces(docs, {"ra": [cap]})
    other = merged["otherData"]
    assert other["reference"] == "router"
    assert other["sources"]["ra"]["shift_us"] == 2e6
    by_name = {e["name"]: e for e in merged["traceEvents"]
               if e.get("ph") == "X"}
    # ra's ts=0 is wall 1002.0 = router ts 2_000_000; the capture rides
    # ra's shift; pids are disjoint per source
    assert by_name["decode"]["ts"] == 2e6
    assert by_name["fusion"]["ts"] == 50.0 + 2e6
    assert by_name["dispatch (200)"]["ts"] == 1000.0
    assert len({e["pid"] for e in merged["traceEvents"]}) == 3
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("name") == "process_name"]
    assert names == ["router:ds_router"], names
    try:
        merge_traces(docs, {"nosuch": [cap]})
    except ValueError:
        pass
    else:
        raise AssertionError("unknown --capture source must be rejected")
    # continuous-profiler history merge: two replicas whose windows start
    # 3s apart on the wall clock; the slow replica's low busy ratio must
    # surface as device-busy skew and its window land at offset_s=3
    def _pwin(seq, lo, busy):
        return {"seq": seq, "engine": "serving", "step": 10 * seq,
                "steps": 2, "window_s": 0.1, "busy_ratio": busy,
                "coverage_ratio": 0.01, "overhead_ratio": 0.005,
                "scopes": {"comm": 0.01},
                "clock": {"anchor_unix": lo, "window_unix_lo": lo,
                          "window_unix_hi": lo + 0.1}}
    hist = {"ra": {"engines": ["serving"],
                   "windows": [_pwin(1, 500.0, 0.9), _pwin(2, 600.0, 0.8)]},
            "rb": {"engines": ["serving"],
                   "windows": [_pwin(1, 503.0, 0.2)]}}
    pm = merge_profiles(hist)
    assert pm["reference"] == "ra" and pm["reference_unix_lo"] == 500.0
    assert pm["latest"]["ra"]["seq"] == 2
    offs = {(w["replica"], w["seq"]): w["offset_s"] for w in pm["windows"]}
    assert offs[("rb", 1)] == 3.0 and offs[("ra", 1)] == 0.0
    assert abs(pm["device_busy"]["skew"] - (0.8 - 0.2) / 0.5) < 1e-9
    out = render_profiles(pm)
    assert "straggler signal" in out and "rb" in out, out
    print("fleet_dump selftest: OK")
    return 0


# ---------------------------------------------------------------------------


def trace_main(args: List[str], flags: set) -> int:
    """``--trace``: scrape every source's perfetto export and merge them
    (plus any ``--capture=<source>=<file>`` device captures) into one
    session, written to ``--out=<file>`` or stdout."""
    docs: Dict[str, Dict[str, object]] = {}
    for i, src in enumerate(args):
        name, sep, rest = src.partition("=")
        if sep and not name.startswith("http") and "/" not in name:
            src = rest
        else:
            name = f"r{i}"
        kind = ""
        if src.endswith("#train"):
            src, kind = src[: -len("#train")], "train"
        if is_url(src):
            docs[name] = fetch_trace(src, kind=kind)
        else:
            with open(src) as fh:
                docs[name] = json.load(fh)
    captures: Dict[str, List[dict]] = {}
    for f in sorted(flags):
        if not f.startswith("--capture="):
            continue
        cname, sep, cpath = f.split("=", 1)[1].partition("=")
        if not sep:
            print("--capture needs <source>=<file>", file=sys.stderr)
            return 2
        captures.setdefault(cname, []).append(load_capture(cpath))
    try:
        merged = merge_traces(docs, captures)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    out_paths = [f.split("=", 1)[1] for f in flags
                 if f.startswith("--out=")]
    body = json.dumps(merged, sort_keys=True)
    if out_paths:
        with open(out_paths[0], "w") as fh:
            fh.write(body)
        srcs = merged["otherData"]["sources"]
        print(f"merged {len(docs)} trace source(s) "
              f"+ {sum(len(v) for v in captures.values())} capture(s) "
              f"-> {out_paths[0]} (reference "
              f"{merged['otherData']['reference']}; shifts_us "
              + ", ".join(f"{n}={s['shift_us']}"
                          for n, s in srcs.items()) + ")")
    else:
        print(body)
    return 0


def main(argv: List[str]) -> int:
    args = [a for a in argv[1:] if not a.startswith("--")]
    flags = {a for a in argv[1:] if a.startswith("--")}
    if "--selftest" in flags:
        return selftest()
    if "--trace" in flags:
        return trace_main(args, flags)
    if "--profiles" in flags:
        return profiles_main(args, flags)
    # --supervisor-status=<file>: supervisor truth (ladder counters,
    # replica/child states) rendered next to the scrape — readable alone
    # too (a down fleet has no /statz to scrape, but the file survives)
    status_paths = [f.split("=", 1)[1] for f in flags
                    if f.startswith("--supervisor-status=")]
    statuses = []
    for p in status_paths:
        try:
            with open(p) as fh:
                st = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"unreadable status file {p}: {exc}", file=sys.stderr)
            return 2
        if isinstance(st, dict):
            st.setdefault("scraped_at", _stamp_now())
        statuses.append(st)
    if not args and statuses:
        if "--json" in flags:
            print(json.dumps({"supervisors": statuses}, sort_keys=True,
                             default=str))
        else:
            for st in statuses:
                print(render_supervisor_status(st))
        return 0
    if not args or "--help" in flags or "-h" in argv[1:]:
        print(__doc__.strip())
        return 0 if args else 2
    snaps: Dict[str, Dict[str, object]] = {}
    kinds: Dict[str, str] = {}
    stamps: Dict[str, Dict[str, float]] = {}
    for i, src in enumerate(args):
        name, sep, rest = src.partition("=")
        if sep and not name.startswith("http"):
            src = rest
        else:
            name = f"r{i}"
        data = load_source(src)
        snaps[name] = data.get("metrics", {})
        kinds.update(data.get("kinds") or {})
        stamps[name] = _stamp_now()
    fleet = merge_snapshots(snaps, kinds)
    if not fleet:
        print("(no metrics found on any replica)")
        return 1
    if "--json" in flags:
        print(json.dumps({"replicas": sorted(snaps), "fleet": fleet,
                          "scraped_at": stamps,
                          **({"supervisors": statuses} if statuses else {})},
                         sort_keys=True, default=str))
    else:
        for st in statuses:
            print(render_supervisor_status(st))
        walls = [s["wall"] for s in stamps.values()]
        if walls:
            print(f"scraped_at={min(walls):.3f} "
                  f"(scrape skew {(max(walls) - min(walls)) * 1e3:.1f}ms "
                  f"over {len(walls)} source(s))")
        print(render(fleet, sorted(snaps)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
