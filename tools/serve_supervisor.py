#!/usr/bin/env python
"""Serving-fleet replica supervisor: spawn, watch, restart, and scale N
replica processes (ROADMAP item 1's control-plane loop; docs/RESILIENCE.md
"Serving fleet").

    python tools/serve_supervisor.py --replicas 2 --base-port 9101 -- \\
        python serve_replica.py --port {port}
    python tools/serve_supervisor.py --selftest          # tier-1 wired

Each replica is one process serving the ``init_serving(metrics_port=...)``
surface on its assigned port (``{port}``/``{index}`` substituted into the
command template; the child also sees ``DS_REPLICA_INDEX`` /
``DS_REPLICA_PORT``).  The supervisor's loop, every ``--poll-interval``:

- **liveness** — a replica whose process exited is restarted through the
  SHARED restart ladder (``deepspeed_tpu/elasticity/supervisor.py``
  ``RestartPolicy`` — the exact ``train_supervisor`` exit-code contract:
  bounded crash restarts with exponential backoff, preempt exits restart
  free, and ``--healthy-reset`` forgives the ladder after a long healthy
  run so a once-a-day crash cannot exhaust a lifetime budget).
- **wedge detection** — a process that is alive but whose ``/healthz``
  has not ANSWERED (any status; 503-draining is an answer) for
  ``--wedge-timeout`` seconds is wedged (serving loop hung, socket
  black-holed): SIGKILL + crash restart.  Liveness is the HTTP server
  answering at all — readiness (200 vs 503) is the router's concern,
  not ours.
- **scaling** — with ``--max-replicas`` above ``--replicas``, the
  supervisor scrapes each ready replica's ``/statz`` and scales OUT when
  the fleet's mean queue depth sits above ``--scale-up-queue`` (or KV
  pool pressure above ``--kv-high``) for ``--scale-sustain`` seconds,
  and scales IN (down to ``--min-replicas``) when it sits below
  ``--scale-down-queue``.  Scale-in is a graceful SIGTERM: the replica
  drains (zero-drop — the router re-dispatches its queued work) and
  exits on its own; only past the grace window is it killed.
- **role-split fleets** — ``--prefill-replicas N --decode-replicas M``
  runs the disaggregated-serving topology (docs/RESILIENCE.md
  "Disaggregated serving") as two independently-scaled pools: each
  replica's command template may use ``{role}`` (also exported as
  ``DS_REPLICA_ROLE``) to start as a prefill or a decode replica, and
  the scale loop evaluates each pool over its OWN members — a prefill
  pool's pressure shows up as admission-queue depth, a decode pool's as
  KV-pool occupancy, and each pool has its own sustain windows and
  ``--max-prefill-replicas`` / ``--max-decode-replicas`` bounds.  With
  both role counts at 0 (the default) the supervisor runs the legacy
  single ``both`` pool, bit-for-bit.
- **graceful shutdown** — SIGTERM to the supervisor forwards SIGTERM to
  every replica (drain → exit), waits out the grace window, SIGKILLs
  stragglers, and exits without restarting anything.

Zero dependencies beyond the stdlib — no jax import (the
``fleet_dump``/``router`` rule; dslint DSL003 pins the import closure).
``--selftest`` drives the real supervisor over synthetic stdlib replica
processes through kill/restart, wedge detection, scale-out/in, and
graceful shutdown; it is wired into tier-1.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

SIGTERM_GRACE_S = 30.0


def _load_supervisor_core():
    """The shared restart-ladder module (the ``tools/train_supervisor.py``
    loader, verbatim): via the package when importable, else by file
    path — no jax on an operator box."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.elasticity import supervisor

        return supervisor
    mod = sys.modules.get("_ds_supervisor_core")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "elasticity", "supervisor.py")
    spec = importlib.util.spec_from_file_location("_ds_supervisor_core", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_supervisor_core"] = mod
    spec.loader.exec_module(mod)
    return mod


_core = _load_supervisor_core()
RestartPolicy = _core.RestartPolicy
PREEMPT_EXIT_CODE = _core.PREEMPT_EXIT_CODE


def _load_goodput_core():
    """The goodput-ledger row schema (monitor/goodput_core.py), loaded
    the same jax-free way as the supervisor core (see
    ``tools/train_supervisor.py``)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.monitor import goodput_core

        return goodput_core
    mod = sys.modules.get("_ds_goodput_core")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "monitor", "goodput_core.py")
    spec = importlib.util.spec_from_file_location("_ds_goodput_core", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_goodput_core"] = mod
    spec.loader.exec_module(mod)
    return mod


def _http_json(url: str, timeout: float):
    """GET ``url`` -> (status_code, parsed_json | {}); (None, {}) when the
    endpoint did not answer at all (refused / timed out / reset)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            try:
                return resp.status, json.load(resp)
            except ValueError:
                return resp.status, {}
    except urllib.error.HTTPError as exc:
        try:
            return exc.code, json.load(exc)
        except Exception:
            return exc.code, {}
    except OSError:
        return None, {}


class _Sustain:
    """A condition must hold continuously for ``sustain_s`` before it
    fires (scale decisions must not flap on one noisy scrape)."""

    def __init__(self, sustain_s: float):
        self.sustain_s = float(sustain_s)
        self.since: Optional[float] = None

    def update(self, cond: bool, now: float) -> bool:
        if not cond:
            self.since = None
            return False
        if self.since is None:
            self.since = now
        return now - self.since >= self.sustain_s


class _Pool:
    """One role's scaling state: its replica target, bounds, and the
    sustain windows its scale decisions flap-guard through.  A legacy
    fleet is one ``both`` pool; a role-split fleet runs a ``prefill``
    and a ``decode`` pool side by side, each scaled over its own
    members' signals."""

    def __init__(self, role: str, target: int, lo: int, hi: int,
                 sustain_s: float):
        self.role = role
        self.target = int(target)
        self.min = int(lo)
        self.max = int(hi)
        self.up = _Sustain(sustain_s)
        self.down = _Sustain(sustain_s)


class ReplicaHandle:
    """One supervised replica slot: its process, its restart ladder, and
    the supervisor's last view of its health/load."""

    RUNNING = "running"
    BACKOFF = "backoff"      # crashed; waiting out the ladder delay
    DRAINING = "draining"    # scale-in SIGTERM sent; exiting on its own
    RETIRED = "retired"      # drained out on purpose; slot removed
    FAILED = "failed"        # ladder exhausted; left down (still counts
    #                          toward target — the fleet runs degraded and
    #                          visibly, instead of crash-looping a fresh
    #                          ladder on a replacement slot forever)

    def __init__(self, index: int, port: int, cmd: List[str],
                 policy: RestartPolicy, role: str = "both"):
        self.index = index
        self.port = port
        self.cmd = cmd
        self.role = role
        self.policy = policy
        self.proc: Optional[subprocess.Popen] = None
        self.state = ReplicaHandle.BACKOFF
        self.restart_at = 0.0            # monotonic; 0 = spawn on next tick
        self.spawned_at = 0.0
        self.last_answer = 0.0           # last /healthz ANSWER (any status)
        self.ready = False               # last /healthz was 200
        self.queue_depth = 0.0
        self.kv_busy = 0.0
        self.drain_deadline = 0.0
        self.wedge_kills = 0

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def snapshot(self) -> Dict[str, object]:
        return {"index": self.index, "port": self.port, "state": self.state,
                "role": self.role,
                "ready": self.ready, "pid":
                    (self.proc.pid if self.proc is not None else None),
                "restarts": self.policy.restarts,
                "crash_restarts": self.policy.crash_restarts,
                "wedge_kills": self.wedge_kills,
                "queue_depth": self.queue_depth,
                "kv_busy": round(self.kv_busy, 4)}


class ServeSupervisor:
    """Spawn/watch/restart/scale a fleet of replica processes (module
    docstring has the full contract)."""

    def __init__(self, cmd_template: List[str], replicas: int = 1,
                 base_port: int = 9101, max_restarts: int = 5,
                 backoff_base: float = 1.0, backoff_max: float = 30.0,
                 healthy_reset_s: Optional[float] = 300.0,
                 poll_interval: float = 0.5, poll_timeout: float = 2.0,
                 wedge_timeout: float = 30.0, grace_s: float = SIGTERM_GRACE_S,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 scale_up_queue: float = 0.0, scale_down_queue: float = 0.0,
                 kv_high: float = 0.92, scale_sustain_s: float = 10.0,
                 prefill_replicas: int = 0, decode_replicas: int = 0,
                 min_prefill_replicas: Optional[int] = None,
                 max_prefill_replicas: Optional[int] = None,
                 min_decode_replicas: Optional[int] = None,
                 max_decode_replicas: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 sleep=time.sleep, status_file: Optional[str] = None,
                 runledger: Optional[str] = None,
                 run_id: Optional[str] = None):
        if not cmd_template:
            raise ValueError("no replica command template given")
        self.cmd_template = list(cmd_template)
        self.base_port = int(base_port)
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.healthy_reset_s = healthy_reset_s
        self.poll_interval = float(poll_interval)
        self.poll_timeout = float(poll_timeout)
        self.wedge_timeout = float(wedge_timeout)
        self.grace_s = float(grace_s)
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else replicas)
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else replicas)
        self.scale_up_queue = float(scale_up_queue)
        self.scale_down_queue = float(scale_down_queue)
        self.kv_high = float(kv_high)
        pre, dec = int(prefill_replicas or 0), int(decode_replicas or 0)
        self.role_split = pre > 0 or dec > 0
        if self.role_split:
            if pre <= 0 or dec <= 0:
                raise ValueError("role-split fleets need BOTH "
                                 "prefill_replicas and decode_replicas > 0")
            self.pools = {
                "prefill": _Pool(
                    "prefill", pre,
                    min_prefill_replicas if min_prefill_replicas
                    is not None else pre,
                    max_prefill_replicas if max_prefill_replicas
                    is not None else pre, scale_sustain_s),
                "decode": _Pool(
                    "decode", dec,
                    min_decode_replicas if min_decode_replicas
                    is not None else dec,
                    max_decode_replicas if max_decode_replicas
                    is not None else dec, scale_sustain_s)}
        else:
            self.pools = {"both": _Pool("both", int(replicas),
                                        self.min_replicas,
                                        self.max_replicas,
                                        scale_sustain_s)}
        self.base_env = dict(env if env is not None else os.environ)
        self.sleep = sleep
        self.status_file = status_file
        # goodput-ledger channel (see tools/train_supervisor.py): one
        # shared jsonl for the fleet, run identity per REPLICA
        # (`<run_id>-r<index>`) so goodput_report stitches each replica's
        # incarnations independently (stitch() filters by run_id)
        self.runledger = runledger or self.base_env.get("DSTPU_RUNLEDGER")
        self.run_id = (run_id or self.base_env.get("DSTPU_RUN_ID")
                       or (f"serve-{os.getpid()}-{int(time.time())}"
                           if self.runledger else None))
        self.replicas: List[ReplicaHandle] = []
        self.total_restarts = 0          # crash+wedge+preempt respawns
        self.scale_outs = 0
        self.scale_ins = 0
        self._next_index = 0
        self._terminating = False
        for pool in self.pools.values():
            for _ in range(pool.target):
                self._new_handle(pool.role)

    @property
    def target(self) -> int:
        """Total wanted replicas across every role pool."""
        return sum(p.target for p in self.pools.values())

    # -- lifecycle ------------------------------------------------------
    def _log(self, msg: str) -> None:
        print(f"[serve_supervisor] {msg}", file=sys.stderr, flush=True)

    def _new_handle(self, role: str = "both") -> ReplicaHandle:
        idx = self._next_index
        self._next_index += 1
        port = self.base_port + idx
        cmd = [a.replace("{port}", str(port)).replace("{index}", str(idx))
               .replace("{role}", role)
               for a in self.cmd_template]
        policy = RestartPolicy(max_restarts=self.max_restarts,
                               backoff_base=self.backoff_base,
                               backoff_max=self.backoff_max,
                               healthy_reset_s=self.healthy_reset_s)
        h = ReplicaHandle(idx, port, cmd, policy, role=role)
        self.replicas.append(h)
        return h

    def _replica_run_id(self, h: ReplicaHandle) -> str:
        return f"{self.run_id}-r{h.index}"

    def _ledger_append(self, h: ReplicaHandle, event: str, **extra) -> None:
        """Restart-decision row into the fleet's run ledger jsonl (no-op
        without --runledger / DSTPU_RUNLEDGER)."""
        if not self.runledger:
            return
        gp = _load_goodput_core()
        gp.append_row(self.runledger, gp.supervisor_row(
            self._replica_run_id(h), event, time.time(),
            supervisor="serve_supervisor", replica=h.index,
            incarnation=h.policy.restarts, **extra))

    def _spawn(self, h: ReplicaHandle, now: float) -> None:
        env = dict(self.base_env)
        env["DS_REPLICA_INDEX"] = str(h.index)
        env["DS_REPLICA_PORT"] = str(h.port)
        env["DS_REPLICA_ROLE"] = h.role
        env["DS_SUPERVISOR_RESTART"] = str(h.policy.restarts)
        if self.runledger:
            env["DSTPU_RUNLEDGER"] = self.runledger
            env["DSTPU_RUN_ID"] = self._replica_run_id(h)
        h.proc = subprocess.Popen(h.cmd, env=env)
        h.state = ReplicaHandle.RUNNING
        h.spawned_at = now
        h.last_answer = now              # the wedge clock starts at spawn
        h.ready = False
        self._log(f"replica {h.index} (port {h.port}): started pid "
                  f"{h.proc.pid} (incarnation {h.policy.restarts})")

    def request_stop(self) -> None:
        """Graceful shutdown from any thread (the SIGTERM handler's body):
        the run loop forwards SIGTERM to every replica, waits out the
        grace window, and exits without restarting."""
        self._terminating = True

    def _forward_sigterm(self, _sig, _frame) -> None:
        self.request_stop()

    # -- one supervision tick -------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._reap(now)
        self._poll_health(now)
        self._detect_wedged(now)
        self._scale(now)
        self._reconcile(now)
        self._write_status("running")

    def _write_status(self, state: str) -> None:
        """Fleet truth as JSON (--status-file, atomic tmp+replace): the
        same ``snapshot()`` the selftest asserts on, plus per-replica
        ladder counters — operators and ``fleet_dump`` read state instead
        of scraping logs."""
        if self.status_file is None:
            return
        snap = self.snapshot()
        snap.update({"kind": "serve_supervisor", "state": state,
                     "pid": os.getpid()})
        for h, entry in zip(self.replicas, snap["replicas"]):
            entry["ladder"] = h.policy.counters()
        _core.write_status(self.status_file, snap)

    def _reap(self, now: float) -> None:
        for h in self.replicas:
            if h.proc is None or h.proc.poll() is None:
                continue
            code = h.proc.returncode
            h.proc = None
            h.ready = False
            if h.state == ReplicaHandle.DRAINING:
                self._log(f"replica {h.index}: drained and exited {code} "
                          f"(scale-in complete)")
                h.state = ReplicaHandle.RETIRED  # slot removed below
                continue
            if self._terminating:
                continue
            if code == 0:
                # a serving replica has no natural "done": an exit 0 with
                # the slot still wanted is respawned immediately, outside
                # the crash ladder (operator-initiated restarts)
                self._log(f"replica {h.index}: exited 0; respawning")
                self.total_restarts += 1
                h.state = ReplicaHandle.BACKOFF
                h.restart_at = now
                self._ledger_append(h, "restart", decision="respawn",
                                    exit_code=0, backoff_s=0.0)
                continue
            decision = h.policy.decide(code, ran_s=now - h.spawned_at)
            if decision.action == "give_up":
                self._log(f"replica {h.index}: crash ladder exhausted "
                          f"(exit {code}); leaving it down")
                h.state = ReplicaHandle.FAILED
                self._ledger_append(h, "give_up", exit_code=code)
                continue
            self.total_restarts += 1
            h.state = ReplicaHandle.BACKOFF
            h.restart_at = now + decision.delay
            self._ledger_append(h, "restart", decision=decision.kind,
                                exit_code=code, backoff_s=decision.delay)
            self._log(f"replica {h.index}: exited {code} ({decision.kind}); "
                      f"restart #{h.policy.restarts} in {decision.delay:g}s")

    def _poll_health(self, now: float) -> None:
        for h in self.replicas:
            if h.state != ReplicaHandle.RUNNING or not h.alive():
                continue
            code, _body = _http_json(h.url + "/healthz",
                                     min(self.poll_timeout,
                                         max(0.05, self.wedge_timeout / 4)))
            if code is not None:         # ANY answer is liveness
                h.last_answer = now
                h.ready = code == 200
            else:
                h.ready = False
            if not h.ready:
                continue
            code, body = _http_json(h.url + "/statz", self.poll_timeout)
            if code != 200:
                continue
            m = body.get("metrics", {}) if isinstance(body, dict) else {}
            h.queue_depth = float(m.get("ds_serve_queue_depth") or 0)
            used = float(m.get("ds_serve_kv_pages_used") or 0)
            free = float(m.get("ds_serve_kv_pages_free") or 0)
            h.kv_busy = used / (used + free) if used + free else 0.0

    def _detect_wedged(self, now: float) -> None:
        for h in self.replicas:
            if h.state != ReplicaHandle.RUNNING or not h.alive():
                continue
            if now - h.last_answer <= self.wedge_timeout:
                continue
            # alive but not answering: the serving/HTTP side is hung —
            # a restart is the only way this replica serves again
            self._log(f"replica {h.index}: wedged ({now - h.last_answer:.1f}s "
                      f"without a /healthz answer); SIGKILL + restart")
            h.wedge_kills += 1
            try:
                h.proc.kill()
            except ProcessLookupError:
                pass
            h.proc.wait()
            # feed the kill through the crash ladder (a wedge IS a crash)
            h.proc = None
            h.ready = False
            decision = h.policy.decide(137, ran_s=now - h.spawned_at)
            if decision.action == "give_up":
                self._log(f"replica {h.index}: crash ladder exhausted "
                          f"after wedge; leaving it down")
                h.state = ReplicaHandle.FAILED
                self._ledger_append(h, "give_up", exit_code=137,
                                    wedge=True)
                continue
            self.total_restarts += 1
            h.state = ReplicaHandle.BACKOFF
            h.restart_at = now + decision.delay
            self._ledger_append(h, "restart", decision="wedge",
                                exit_code=137, backoff_s=decision.delay)

    def _scale(self, now: float) -> None:
        if self._terminating:
            return
        for pool in self.pools.values():
            self._scale_pool(pool, now)

    def _scale_pool(self, pool: _Pool, now: float) -> None:
        """One pool's scale decision over its OWN members' signals.  The
        same watermarks apply to every pool, but the signals separate by
        role naturally: a prefill pool's pressure shows up as
        admission-queue depth (it runs admission + chunked prefill), a
        decode pool's as KV-pool occupancy (it holds every active
        generation's pages) — so a shared-prefix burst scales the
        prefill pool while long generations scale the decode pool."""
        if pool.max <= pool.min:
            return
        ready = [h for h in self.replicas if h.ready
                 and h.state == ReplicaHandle.RUNNING
                 and h.role == pool.role]
        if not ready:
            pool.up.update(False, now)
            pool.down.update(False, now)
            return
        mean_q = sum(h.queue_depth for h in ready) / len(ready)
        max_kv = max(h.kv_busy for h in ready)
        want_up = (self.scale_up_queue > 0 and mean_q >= self.scale_up_queue) \
            or max_kv >= self.kv_high
        # scale-in is opt-in exactly like scale-out: 0 disables (an
        # operator scaling out on KV pressure alone must not have idle
        # queues silently SIGTERM their warm replicas)
        want_down = (self.scale_down_queue > 0
                     and mean_q <= self.scale_down_queue
                     and max_kv < self.kv_high)
        label = f" [{pool.role}]" if self.role_split else ""
        if pool.up.update(want_up, now) and pool.target < pool.max:
            pool.target += 1
            self.scale_outs += 1
            pool.up.since = None         # re-sustain before the next step
            self._log(f"scale OUT{label} -> {pool.target} (mean queue "
                      f"{mean_q:.1f}, kv {max_kv:.2f})")
        elif pool.down.update(want_down, now) \
                and pool.target > pool.min:
            pool.target -= 1
            self.scale_ins += 1
            pool.down.since = None
            self._log(f"scale IN{label} -> {pool.target} "
                      f"(mean queue {mean_q:.1f})")

    def _reconcile(self, now: float) -> None:
        # drop slots that drained out on purpose (scale-in complete);
        # FAILED slots stay — they occupy their target slot so the fleet
        # runs visibly degraded instead of crash-looping replacements
        self.replicas = [h for h in self.replicas
                         if h.state != ReplicaHandle.RETIRED]
        if not self._terminating:
            for pool in self.pools.values():
                members = [h for h in self.replicas if h.role == pool.role]
                live = [h for h in members if h.state in
                        (ReplicaHandle.RUNNING, ReplicaHandle.BACKOFF)]
                occupying = live + [h for h in members
                                    if h.state == ReplicaHandle.FAILED]
                while len(occupying) < pool.target:
                    h = self._new_handle(pool.role)
                    live.append(h)
                    occupying.append(h)
                # scale-in: SIGTERM the youngest slot — drain is
                # zero-drop (the router re-dispatches its queued work)
                # and the replica exits on its own; stragglers are
                # killed past the grace
                surplus = len(occupying) - pool.target
                for h in sorted(live,
                                key=lambda x: -x.index)[:max(0, surplus)]:
                    if h.state == ReplicaHandle.RUNNING and h.alive():
                        self._log(f"replica {h.index} ({h.role}): scale-in "
                                  f"SIGTERM (drain -> exit)")
                        try:
                            h.proc.send_signal(signal.SIGTERM)
                        except ProcessLookupError:
                            pass
                        h.state = ReplicaHandle.DRAINING
                        h.drain_deadline = now + self.grace_s
                    elif h.state == ReplicaHandle.BACKOFF:
                        self.replicas.remove(h)  # never spawned: drop
        for h in self.replicas:
            if h.state == ReplicaHandle.DRAINING and h.alive() \
                    and now > h.drain_deadline:
                self._log(f"replica {h.index}: drain grace expired; killing")
                try:
                    h.proc.kill()
                except ProcessLookupError:
                    pass
            if h.state == ReplicaHandle.BACKOFF and now >= h.restart_at \
                    and not self._terminating:
                self._spawn(h, now)

    # -- main loop ------------------------------------------------------
    def run(self) -> int:
        prev = None
        try:
            prev = signal.signal(signal.SIGTERM, self._forward_sigterm)
        except ValueError:               # non-main thread (selftest)
            prev = None
        try:
            while not self._terminating:
                self.tick()
                self.sleep(self.poll_interval)
            return self._shutdown()
        finally:
            if prev is not None:
                try:
                    signal.signal(signal.SIGTERM, prev)
                except ValueError:
                    pass

    def _shutdown(self) -> int:
        """SIGTERM every replica (graceful drain → exit), wait out the
        grace window, SIGKILL stragglers.  Never restarts."""
        victims = [h for h in self.replicas if h.alive()]
        self._log(f"shutting down: SIGTERM -> {len(victims)} replica(s)")
        for h in victims:
            try:
                h.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
        deadline = time.monotonic() + self.grace_s
        for h in victims:
            left = deadline - time.monotonic()
            try:
                h.proc.wait(timeout=max(0.0, left))
            except subprocess.TimeoutExpired:
                self._log(f"replica {h.index}: grace expired; killing")
                h.proc.kill()
                h.proc.wait()
        self._log("shutdown complete")
        self._write_status("shutdown")
        return 0

    def snapshot(self) -> Dict[str, object]:
        return {"target": self.target,
                "targets": {p.role: p.target for p in self.pools.values()},
                "role_split": self.role_split,
                "total_restarts": self.total_restarts,
                "scale_outs": self.scale_outs, "scale_ins": self.scale_ins,
                "replicas": [h.snapshot() for h in self.replicas]}


# ---------------------------------------------------------------------------
# selftest (tier-1 wired: tests/unit/test_serve_supervisor.py)
# ---------------------------------------------------------------------------

# a synthetic replica: stdlib HTTP /healthz + /statz whose load/wedge
# behavior is driven by a JSON file the selftest mutates at runtime, and
# whose SIGTERM handler drains (healthz 503) then exits 0 — the graceful
# scale-in / shutdown contract a real replica implements via
# ServingEngine.drain()
_FAKE_REPLICA_PROG = r"""
import json, os, signal, sys, threading, time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

port, beh_path, marker = int(sys.argv[1]), sys.argv[2], sys.argv[3]
index = int(os.environ.get("DS_REPLICA_INDEX", "-1"))
role = os.environ.get("DS_REPLICA_ROLE", "both")
state = {"draining": False}

def beh():
    try:
        with open(beh_path) as fh:
            b = json.load(fh)
    except Exception:
        return {}
    # per-role overlay: {"roles": {"decode": {"kv_used": 9}}} pressures
    # one pool without touching the other (role-split selftest)
    b.update(b.get("roles", {}).get(role, {}))
    return b

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        b = beh()
        if b.get("wedge_index") == index:
            time.sleep(3600)
        path = self.path.partition("?")[0]
        if path == "/healthz":
            code = 503 if state["draining"] else 200
            body = json.dumps({"ready": code == 200}).encode()
        elif path == "/statz":
            code = 200
            body = json.dumps({"enabled": True, "metrics": {
                "ds_serve_queue_depth": b.get("queue_depth", 0),
                "ds_serve_active_slots": 0,
                "ds_serve_kv_pages_used": b.get("kv_used", 0),
                "ds_serve_kv_pages_free": b.get("kv_free", 8),
            }}).encode()
        else:
            self.send_error(404)
            return
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        pass

def on_term(_sig, _frm):
    state["draining"] = True
    def die():
        time.sleep(0.1)                      # the "drain window"
        with open(marker, "a") as fh:
            fh.write("drained %s\n" % os.environ.get("DS_REPLICA_INDEX", "?"))
        os._exit(0)
    threading.Thread(target=die, daemon=True).start()

signal.signal(signal.SIGTERM, on_term)
srv = ThreadingHTTPServer(("127.0.0.1", port), H)
srv.serve_forever()
"""


def _free_port_block(n: int) -> int:
    """A base port with ``n`` consecutive free ports (probed by binding;
    inherently racy, retried by the caller on spawn failure)."""
    import random
    import socket

    for _attempt in range(64):
        base = random.randint(22000, 52000)
        ok = True
        for p in range(base, base + n):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", p))
            except OSError:
                ok = False
                break
            finally:
                s.close()
        if ok:
            return base
    raise RuntimeError("no free port block found")


def _wait(cond, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def selftest() -> int:
    import tempfile

    if os.path.basename(sys.argv[0]).startswith("serve_supervisor"):
        # standalone contract: this tool must never drag jax in
        assert "jax" not in sys.modules, "serve_supervisor imported jax"
    with tempfile.TemporaryDirectory() as td:
        beh_path = os.path.join(td, "behavior.json")
        marker = os.path.join(td, "drained.txt")
        with open(beh_path, "w") as fh:
            json.dump({}, fh)
        base = _free_port_block(4)
        status_path = os.path.join(td, "status.json")
        sup = ServeSupervisor(
            [sys.executable, "-c", _FAKE_REPLICA_PROG, "{port}", beh_path,
             marker],
            replicas=2, base_port=base, max_restarts=4, backoff_base=0.05,
            backoff_max=0.2, healthy_reset_s=None, poll_interval=0.05,
            poll_timeout=0.5, wedge_timeout=1.5, grace_s=5.0,
            min_replicas=2, max_replicas=3, scale_up_queue=4.0,
            scale_down_queue=1.0, scale_sustain_s=0.2,
            status_file=status_path)
        thread = threading.Thread(target=sup.run, daemon=True)
        thread.start()
        try:
            # 1) both replicas come up ready
            _wait(lambda: sum(h.ready for h in sup.replicas) == 2, 15,
                  "2 replicas ready")
            # --status-file: fleet truth is published as readable JSON
            # every tick (replica states + per-replica ladder counters)
            _wait(lambda: os.path.exists(status_path), 10, "status file")
            st = json.load(open(status_path))
            assert st["kind"] == "serve_supervisor"
            assert st["state"] == "running" and st["target"] == 2
            assert len(st["replicas"]) == 2
            assert all("ladder" in r for r in st["replicas"])
            # 2) SIGKILL replica 0 -> crash restart through the ladder
            h0 = sup.replicas[0]
            pid0 = h0.proc.pid
            os.kill(pid0, signal.SIGKILL)
            _wait(lambda: h0.ready and h0.proc is not None
                  and h0.proc.pid != pid0, 15, "replica 0 restarted")
            assert h0.policy.crash_restarts >= 1
            assert sup.total_restarts >= 1
            # 3) wedge: replica 1 stops answering -> SIGKILL + restart
            wedged = sup.replicas[1]
            with open(beh_path, "w") as fh:
                json.dump({"wedge_index": wedged.index}, fh)
            _wait(lambda: wedged.wedge_kills >= 1, 20, "wedge kill")
            with open(beh_path, "w") as fh:
                json.dump({}, fh)
            _wait(lambda: all(h.ready for h in sup.replicas
                              if h.state == ReplicaHandle.RUNNING)
                  and sum(h.ready for h in sup.replicas) >= 2, 20,
                  "fleet healthy after wedge")
            # 4) sustained queue depth above the watermark -> scale OUT
            with open(beh_path, "w") as fh:
                json.dump({"queue_depth": 9}, fh)
            _wait(lambda: sup.target == 3
                  and sum(h.ready for h in sup.replicas) == 3, 20,
                  "scale-out to 3")
            assert sup.scale_outs == 1
            # 5) load drops -> graceful scale IN (victim drains, exits 0)
            with open(beh_path, "w") as fh:
                json.dump({"queue_depth": 0}, fh)
            _wait(lambda: sup.scale_ins == 1
                  and sum(1 for h in sup.replicas if h.alive()) == 2, 20,
                  "scale-in to 2")
            _wait(lambda: os.path.exists(marker)
                  and "drained" in open(marker).read(), 10,
                  "scale-in victim drained")
            # 6) graceful shutdown: SIGTERM fans out, every child drains
            pids = [h.proc.pid for h in sup.replicas if h.alive()]
            sup.request_stop()
            thread.join(timeout=20)
            assert not thread.is_alive(), "supervisor did not shut down"
            for pid in pids:
                try:
                    os.kill(pid, 0)
                    raise AssertionError(f"child {pid} survived shutdown")
                except ProcessLookupError:
                    pass
            drained = open(marker).read().count("drained")
            assert drained >= 3, f"expected >=3 drains, saw {drained}"
            # terminal status reflects the shutdown + the restart history
            st = json.load(open(status_path))
            assert st["state"] == "shutdown"
            assert st["total_restarts"] >= 2 and st["scale_outs"] == 1
        finally:
            sup.request_stop()
            thread.join(timeout=20)
            for h in sup.replicas:
                if h.alive():
                    h.proc.kill()
    _selftest_role_split()
    print("serve_supervisor selftest: OK (restart-on-kill, wedge "
          "detection, scale-out/in, role-split pools, graceful shutdown)")
    return 0


def _selftest_role_split() -> None:
    """Role-split pools: 1 prefill + 1 decode come up with their roles in
    the environment and the status file, and sustained KV pressure on
    the DECODE pool alone scales only the decode pool out."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        beh_path = os.path.join(td, "behavior.json")
        marker = os.path.join(td, "drained.txt")
        with open(beh_path, "w") as fh:
            json.dump({}, fh)
        base = _free_port_block(3)
        status_path = os.path.join(td, "status.json")
        sup = ServeSupervisor(
            [sys.executable, "-c", _FAKE_REPLICA_PROG, "{port}", beh_path,
             marker],
            base_port=base, max_restarts=4, backoff_base=0.05,
            backoff_max=0.2, healthy_reset_s=None, poll_interval=0.05,
            poll_timeout=0.5, wedge_timeout=5.0, grace_s=5.0,
            prefill_replicas=1, decode_replicas=1,
            max_decode_replicas=2, kv_high=0.8, scale_sustain_s=0.2,
            status_file=status_path)
        assert sup.target == 2 and sup.role_split
        thread = threading.Thread(target=sup.run, daemon=True)
        thread.start()
        try:
            _wait(lambda: sum(h.ready for h in sup.replicas) == 2, 15,
                  "role-split fleet ready")
            roles = sorted(h.role for h in sup.replicas)
            assert roles == ["decode", "prefill"], roles
            st = json.load(open(status_path))
            assert st["role_split"] is True
            assert st["targets"] == {"prefill": 1, "decode": 1}
            assert sorted(r["role"] for r in st["replicas"]) == roles
            # decode-only KV pressure -> ONLY the decode pool scales out
            with open(beh_path, "w") as fh:
                json.dump({"roles": {"decode":
                                     {"kv_used": 9, "kv_free": 1}}}, fh)
            _wait(lambda: sup.pools["decode"].target == 2
                  and sum(h.ready for h in sup.replicas
                          if h.role == "decode") == 2, 20,
                  "decode pool scale-out")
            assert sup.pools["prefill"].target == 1
            assert sum(1 for h in sup.replicas
                       if h.role == "prefill") == 1
        finally:
            sup.request_stop()
            thread.join(timeout=20)
            for h in sup.replicas:
                if h.alive():
                    h.proc.kill()


# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv if argv is None else argv)
    if "--selftest" in argv[1:]:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="serve_supervisor",
        description="Spawn, watch, restart, and scale N serving replica "
                    "processes ({port}/{index} substituted into the "
                    "command template).")
    parser.add_argument("--replicas", type=int, default=1)
    parser.add_argument("--base-port", type=int, default=9101)
    parser.add_argument("--max-restarts", type=int, default=5)
    parser.add_argument("--backoff-base", type=float, default=1.0)
    parser.add_argument("--backoff-max", type=float, default=30.0)
    parser.add_argument("--healthy-reset", type=float, default=300.0,
                        help="a replica healthy this long resets its crash "
                             "ladder (0 disables)")
    parser.add_argument("--poll-interval", type=float, default=0.5)
    parser.add_argument("--wedge-timeout", type=float, default=30.0,
                        help="alive-but-unresponsive seconds before a "
                             "SIGKILL + restart")
    parser.add_argument("--grace", type=float, default=SIGTERM_GRACE_S)
    parser.add_argument("--min-replicas", type=int, default=None)
    parser.add_argument("--max-replicas", type=int, default=None)
    parser.add_argument("--prefill-replicas", type=int, default=0,
                        help="run a role-split (disaggregated) fleet with "
                             "this many prefill replicas ({role} / "
                             "DS_REPLICA_ROLE tells each child its role; "
                             "requires --decode-replicas too)")
    parser.add_argument("--decode-replicas", type=int, default=0,
                        help="decode replicas of a role-split fleet")
    parser.add_argument("--min-prefill-replicas", type=int, default=None)
    parser.add_argument("--max-prefill-replicas", type=int, default=None)
    parser.add_argument("--min-decode-replicas", type=int, default=None)
    parser.add_argument("--max-decode-replicas", type=int, default=None)
    parser.add_argument("--scale-up-queue", type=float, default=0.0,
                        help="mean fleet queue depth that scales OUT when "
                             "sustained (0 disables queue-driven scaling)")
    parser.add_argument("--scale-down-queue", type=float, default=0.0,
                        help="mean fleet queue depth at or below which the "
                             "fleet scales IN when sustained (0 disables "
                             "queue-driven scale-in)")
    parser.add_argument("--kv-high", type=float, default=0.92,
                        help="KV pool pressure that scales OUT when "
                             "sustained")
    parser.add_argument("--scale-sustain", type=float, default=10.0)
    parser.add_argument("--status-file", default=None,
                        help="write fleet truth (replica states, ladder "
                             "counters, scale events) as JSON to this path "
                             "every tick")
    parser.add_argument("--runledger", default=None,
                        help="goodput-ledger jsonl path shared by the whole "
                             "fleet: each replica incarnation gets "
                             "DSTPU_RUNLEDGER + a per-replica DSTPU_RUN_ID "
                             "(<run-id>-r<index>), and restart decisions "
                             "are appended so tools/goodput_report.py "
                             "stitches each replica across restarts "
                             "(defaults to the DSTPU_RUNLEDGER env var)")
    parser.add_argument("--run-id", default=None,
                        help="base run identity for --runledger rows "
                             "(default: DSTPU_RUN_ID env or a generated id)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by the replica command template")
    args = parser.parse_args(argv[1:])
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        parser.error("no replica command given (… -- python replica.py "
                     "--port {port} …)")
    sup = ServeSupervisor(
        cmd, replicas=args.replicas, base_port=args.base_port,
        max_restarts=args.max_restarts, backoff_base=args.backoff_base,
        backoff_max=args.backoff_max,
        healthy_reset_s=(args.healthy_reset or None),
        poll_interval=args.poll_interval, wedge_timeout=args.wedge_timeout,
        grace_s=args.grace, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas, scale_up_queue=args.scale_up_queue,
        scale_down_queue=args.scale_down_queue, kv_high=args.kv_high,
        scale_sustain_s=args.scale_sustain,
        prefill_replicas=args.prefill_replicas,
        decode_replicas=args.decode_replicas,
        min_prefill_replicas=args.min_prefill_replicas,
        max_prefill_replicas=args.max_prefill_replicas,
        min_decode_replicas=args.min_decode_replicas,
        max_decode_replicas=args.max_decode_replicas,
        status_file=args.status_file,
        runledger=args.runledger, run_id=args.run_id)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
