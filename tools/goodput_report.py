#!/usr/bin/env python
"""Render (or diff) a run-level goodput ledger from its jsonl file.

    python tools/goodput_report.py /runs/runledger.jsonl
    python tools/goodput_report.py /runs/runledger.jsonl --run-id run-42
    python tools/goodput_report.py A.jsonl --diff B.jsonl   # B relative to A
    python tools/goodput_report.py ledger.jsonl --json      # stitched report
    python tools/goodput_report.py --selftest               # tier-1 wired

The ledger (``deepspeed_tpu/monitor/goodput_core.py``, written by
training/serving engines and the supervisors) attributes every second of
run wall clock to one category of a closed set and telescopes to the
run's wall time; ``stitch`` folds all incarnations of one ``run_id``
(supervisor restarts) into a single timeline whose death→healthy-again
gaps become ``restart_downtime``.  This tool is the offline reader: one
run renders as the category table + per-incarnation/gap detail; ``--diff``
compares the category SHARES of two runs (a perf-regression lens over
two bench ledgers).  A jsonl holding several run_ids (a serve fleet's
shared ledger) renders each run in sequence unless ``--run-id`` picks one.

Zero dependencies beyond the stdlib — **no jax import** (``goodput_core``
is stdlib-only on purpose and loads by file path, the fleet_dump idiom;
dslint rule DSL003 pins the whole closure), so a ledger scraped off a
training pod is readable on any operator box.

``--selftest`` synthesizes a two-incarnation ledger, stitches it, and
asserts the telescoping contract + render/diff output (wired into
tier-1 so this offline tool cannot silently rot).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_goodput_core():
    """goodput_core WITHOUT jax: reuse the package module when already
    imported, else load the file by path (stdlib-only by contract)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.monitor import goodput_core

        return goodput_core
    mod = sys.modules.get("_ds_goodput_core")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(_REPO, "deepspeed_tpu", "monitor", "goodput_core.py")
    spec = importlib.util.spec_from_file_location("_ds_goodput_core", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_goodput_core"] = mod
    spec.loader.exec_module(mod)
    return mod


core = _load_goodput_core()


def _run_ids(rows) -> List[str]:
    """Distinct run ids in file order (a fleet ledger holds several)."""
    seen: List[str] = []
    for row in rows:
        rid = row.get("run_id")
        if rid and rid not in seen:
            seen.append(rid)
    return seen


def report_for(path: str, run_id: Optional[str] = None) -> dict:
    rows = core.read_rows(path)
    if not rows:
        raise SystemExit(f"no ledger rows in {path}")
    if run_id is None:
        ids = _run_ids(rows)
        run_id = ids[0] if ids else None
    return core.stitch(rows, run_id=run_id)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv if argv is None else argv)
    if "--selftest" in argv[1:]:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="goodput_report",
        description="Render or diff a run-level goodput ledger "
                    "(runledger.jsonl).")
    parser.add_argument("ledger", help="path to the runledger.jsonl")
    parser.add_argument("--run-id", default=None,
                        help="stitch only this run id (default: every run "
                             "in the file, in order)")
    parser.add_argument("--diff", metavar="LEDGER_B", default=None,
                        help="second ledger: print B's category shares "
                             "relative to the first ledger's")
    parser.add_argument("--json", action="store_true",
                        help="emit the stitched report(s) as JSON")
    args = parser.parse_args(argv[1:])

    if args.diff is not None:
        a = report_for(args.ledger, args.run_id)
        b = report_for(args.diff, args.run_id)
        if args.json:
            print(json.dumps({"a": a, "b": b}, sort_keys=True))
        else:
            print("\n".join(core.diff_lines(a, b)))
        return 0

    rows = core.read_rows(args.ledger)
    if not rows:
        print(f"no ledger rows in {args.ledger}", file=sys.stderr)
        return 1
    ids = [args.run_id] if args.run_id else (_run_ids(rows) or [None])
    reports = [core.stitch(rows, run_id=rid) for rid in ids]
    if args.json:
        print(json.dumps(reports if len(reports) > 1 else reports[0],
                         sort_keys=True))
        return 0
    for i, rep in enumerate(reports):
        if i:
            print()
        print("\n".join(core.render_lines(rep)))
    return 0


# ---------------------------------------------------------------------------
# selftest (tier-1 wired: tests/unit/test_goodput.py)
# ---------------------------------------------------------------------------


def selftest() -> int:
    import tempfile

    if os.path.basename(sys.argv[0]).startswith("goodput_report"):
        assert "jax" not in sys.modules, "goodput_report imported jax"
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "runledger.jsonl")
        # two incarnations of one run with a 5s restart gap, plus a
        # supervisor decision row explaining it
        # real tick rows carry the snapshot categories INCLUDING the idle
        # residual, so each incarnation's categories sum to its uptime
        snap1 = {"categories": {"compute": 8.0, "checkpoint_save": 1.0,
                                "idle": 1.0},
                 "goodput_ratio": 0.8, "tokens": 800, "steps": 8}
        snap2 = {"categories": {"compute": 4.0, "checkpoint_load": 0.5,
                                "idle": 0.5},
                 "goodput_ratio": 0.8, "tokens": 400, "steps": 12}
        for row in (
                core.start_row("r1", 0, "train", 1000.0),
                core.tick_row("r1", 0, 1010.0, 10.0, snap1),
                core.supervisor_row("r1", "restart", 1015.0,
                                    decision="crash", exit_code=7),
                core.start_row("r1", 1, "train", 1015.0),
                core.tick_row("r1", 1, 1020.0, 5.0, snap2)):
            core.append_row(path, row)
        rep = report_for(path)
        assert rep["run_id"] == "r1"
        assert len(rep["incarnations"]) == 2
        assert rep["restart_gaps_s"] == [5.0], rep["restart_gaps_s"]
        assert abs(rep["wall_s"] - 20.0) < 1e-12
        assert abs(rep["categories"]["restart_downtime"] - 5.0) < 1e-12
        assert core.telescopes(rep), rep
        assert rep["tokens"] == 1200 and rep["steps"] == 12
        assert rep["supervisor"] and \
            rep["supervisor"][0]["event"] == "restart"
        text = "\n".join(core.render_lines(rep))
        assert "restart gap 0: 5.000s" in text
        assert "telescopes: True" in text

        # diff: a second ledger with worse goodput shows a negative delta
        path_b = os.path.join(td, "b.jsonl")
        snap_b = {"categories": {"compute": 5.0, "host_stall": 5.0},
                  "goodput_ratio": 0.5, "tokens": 500, "steps": 5}
        core.append_row(path_b, core.start_row("r2", 0, "train", 2000.0))
        core.append_row(path_b, core.tick_row("r2", 0, 2010.0, 10.0, snap_b))
        rep_b = report_for(path_b)
        dtext = "\n".join(core.diff_lines(rep, rep_b))
        assert "->" in dtext and "host_stall" in dtext

        # CLI surface: render + json + diff all go through main()
        assert main(["goodput_report", path]) == 0
        assert main(["goodput_report", path, "--json"]) == 0
        assert main(["goodput_report", path, "--diff", path_b]) == 0

        # torn final line (process died mid-append): reader skips it
        with open(path, "a") as fh:
            fh.write('{"v": 1, "kind": "tick", "run_id": "r1", "trunc')
        assert report_for(path)["wall_s"] == rep["wall_s"]
    print("goodput_report selftest: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
