"""Perf sweep for the GPT-2 125M bench rung (BASELINE.json configs[1]).

Times fwd+bwd microsteps of bench-shaped variants on the real chip to locate
where MFU is lost (transformer stack vs cross-entropy head vs attention
kernel), and sweeps the knobs VERDICT r2 flagged: CE chunk size, vocab
padding, micro-batch, attention impl.

Usage: python tools/perf_sweep.py [--steps 8] [--part all|pieces|sweep|remat]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm

PEAK = 197e12  # v5e bf16


def sync(x):
    float(jax.tree.leaves(x)[0].sum())


def bench_fn(fn, args, steps=8, warmup=2, donate=None):
    jfn = jax.jit(fn, donate_argnums=donate or ())
    out = jfn(*args)
    sync(out)
    # re-make donated args each call outside timing is wrong; for timing we
    # skip donation unless args are regenerated — callers pass donate=None.
    t0 = time.perf_counter()
    for _ in range(steps):
        out = jfn(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps


def model_flops_per_token(cfg, n_params, seq):
    return 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq


def run_variant(name, micro=16, seq=1024, vocab=50257, ce_chunk=None, steps=8,
                impl=None, remat=None, remat_policy=None):
    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    over = dict(vocab_size=vocab)
    if ce_chunk is not None:
        over["ce_chunk"] = ce_chunk
    if remat is not None:
        over["remat"] = remat
    if remat_policy is not None:
        over["remat_policy"] = remat_policy
    model = causal_lm("gpt2-small", mesh=mesh, **over)
    cfg = model.config
    rng = jax.random.PRNGKey(0)
    params = model.init(rng)
    params = jax.tree.map(lambda x: x.astype(jnp.float32), params)
    tokens = jax.random.randint(rng, (micro, seq), 0, 50256)
    if impl is not None:
        import deepspeed_tpu.ops.pallas.common as C
        C._FORCE = impl
        C.default_impl.cache_clear()

    def loss_fn(p, t):
        pc = jax.tree.map(lambda x: x.astype(jnp.bfloat16)
                          if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        return model.apply(pc, t, labels=t)

    grad_fn = jax.value_and_grad(loss_fn)
    dt = bench_fn(lambda p, t: grad_fn(p, t), (params, tokens), steps=steps)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    fpt = model_flops_per_token(cfg, n_params, seq)
    tps = micro * seq / dt
    mfu = tps * fpt / PEAK
    print(f"{name:36s} dt={dt*1e3:7.2f}ms tok/s={tps:9.0f} mfu={mfu:.4f}")
    return dt, mfu


def run_pieces(micro=16, seq=1024, vocab=50257, steps=8):
    """Split timing: transformer stack vs CE head."""
    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    model = causal_lm("gpt2-small", mesh=mesh, vocab_size=vocab)
    cfg = model.config
    rng = jax.random.PRNGKey(0)
    params = model.init(rng)
    tokens = jax.random.randint(rng, (micro, seq), 0, 50256)

    def cast(p):
        return jax.tree.map(lambda x: x.astype(jnp.bfloat16)
                            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)

    # 1. stack only (logits path replaced by sum)
    def stack_loss(p, t):
        pc = cast(p)
        x = jnp.take(pc["embed"]["tok"], t, axis=0)
        x = x + pc["embed"]["pos"][:seq][None]
        cos = sin = jnp.zeros((), x.dtype)
        import functools
        body = functools.partial(model._layer, cos=cos, sin=sin,
                                 batch_ax=("dp", "fsdp", "ep"), use_drop=False)
        keys = jnp.zeros((cfg.num_layers,), jnp.uint32)

        def scan_body(c, xs):
            lp, key = xs
            y, aux = body(lp, c, key)
            return y, aux
        x, _ = jax.lax.scan(scan_body, x, (pc["layers"], keys))
        return x.astype(jnp.float32).sum()

    g1 = jax.grad(stack_loss)
    dt1 = bench_fn(lambda p, t: g1(p, t), (params, tokens), steps=steps)

    # 2. CE head only
    from deepspeed_tpu.models.transformer import blockwise_cross_entropy
    x_in = jax.random.normal(rng, (micro, seq, cfg.hidden_size), jnp.bfloat16)
    head = jax.random.normal(rng, (cfg.hidden_size, vocab), jnp.float32)

    for chunk in (1024, 2048, 4096, 8192):
        def ce_loss(x, h, t, chunk=chunk):
            return blockwise_cross_entropy(x[:, :-1], h.astype(jnp.bfloat16),
                                           t[:, 1:], chunk=chunk)
        g2 = jax.grad(ce_loss, argnums=(0, 1))
        dt2 = bench_fn(lambda x, h, t: g2(x, h, t), (x_in, head, tokens), steps=steps)
        ce_flops = 6 * micro * seq * cfg.hidden_size * vocab
        print(f"  ce chunk={chunk:5d} dt={dt2*1e3:7.2f}ms eff={ce_flops/dt2/PEAK:.3f}")

    def ce_dense(x, h, t):
        logits = (x[:, :-1] @ h.astype(jnp.bfloat16)).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, jnp.maximum(t[:, 1:], 0)[..., None],
                                   axis=-1).squeeze(-1)
        return (lse - gold).mean()
    g3 = jax.grad(ce_dense, argnums=(0, 1))
    dt3 = bench_fn(lambda x, h, t: g3(x, h, t), (x_in, head, tokens), steps=steps)
    ce_flops = 6 * micro * seq * cfg.hidden_size * vocab
    print(f"  ce dense      dt={dt3*1e3:7.2f}ms eff={ce_flops/dt3/PEAK:.3f}")

    stack_flops = micro * seq * (6 * 85e6 + 6 * cfg.num_layers * cfg.hidden_size * seq)
    print(f"  stack (12L)   dt={dt1*1e3:7.2f}ms eff~={stack_flops/dt1/PEAK:.3f}")


def _timed_op(fn, args, flops=0.0, gbytes=0.0, name="", reps=24, steps=4):
    """Time ``fn`` with REPS serialized applications inside ONE program so
    the ~9ms remote-dispatch latency amortizes away.  Serialization: the
    carry scales the first arg, creating a data dependency XLA can't CSE."""
    def many(*a):
        def body(c, _):
            out = fn(a[0] * (1 + c * 1e-20), *a[1:])
            return jnp.asarray(out, jnp.float32).mean(), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=reps)
        return c

    dt = bench_fn(many, args, steps=steps) / reps
    bits = []
    if flops:
        bits.append(f"eff={flops/dt/PEAK:.3f}")
    if gbytes:
        bits.append(f"bw={gbytes/dt:.0f}GB/s")
    print(f"{name:24s} dt={dt*1e3:7.3f}ms {' '.join(bits)}")
    return dt


def run_kernels(steps=4):
    """Microbench the Pallas kernels vs MXU/HBM ideals (bench shapes)."""
    B, H, S, Dh, D = 16, 12, 1024, 64, 768
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (B, H, S, Dh), jnp.bfloat16)
    k = jax.random.normal(rng, (B, H, S, Dh), jnp.bfloat16)
    v = jax.random.normal(rng, (B, H, S, Dh), jnp.bfloat16)

    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    flops = 2 * B * H * S * S * Dh * 2 / 2  # qk + av, causal-halved
    _timed_op(lambda q, k, v: flash_attention(q, k, v, causal=True),
              (q, k, v), flops=flops, name="flash fwd", steps=steps)
    g = jax.grad(lambda q, k, v: flash_attention(q, k, v, causal=True)
                 .astype(jnp.float32).sum(), argnums=(0, 1, 2))
    _timed_op(lambda q, k, v: g(q, k, v)[0], (q, k, v), flops=3.5 * flops,
              name="flash fwd+bwd", steps=steps)

    from deepspeed_tpu.ops.pallas.layer_norm import layer_norm

    x = jax.random.normal(rng, (B * S, D), jnp.bfloat16)
    w = jnp.ones((D,), jnp.float32)
    b = jnp.zeros((D,), jnp.float32)
    gb = 2 * x.size * 2 / 1e9  # read+write bf16
    _timed_op(lambda x: layer_norm(x, w, b), (x,), gbytes=gb,
              name="layernorm fwd", steps=steps)
    gln = jax.grad(lambda x: layer_norm(x, w, b).astype(jnp.float32).sum())
    _timed_op(gln, (x,), gbytes=2 * gb, name="layernorm bwd", steps=steps)

    # plain matmuls at layer shapes for the MXU ceiling
    a = jax.random.normal(rng, (B * S, D), jnp.bfloat16)
    w1 = jax.random.normal(rng, (D, 4 * D), jnp.bfloat16)
    mf = 2 * B * S * D * 4 * D
    _timed_op(lambda a, w1: a @ w1, (a, w1), flops=mf,
              name="matmul 768x3072 fwd", steps=steps)
    gmm = jax.grad(lambda a, w1: (a @ w1).astype(jnp.float32).sum(),
                   argnums=(0, 1))
    _timed_op(lambda a, w1: gmm(a, w1)[0], (a, w1), flops=3 * mf,
              name="matmul 768x3072 f+b", steps=steps)
    w2 = jax.random.normal(rng, (D, D), jnp.bfloat16)
    _timed_op(lambda a, w2: a @ w2, (a, w2), flops=2 * B * S * D * D,
              name="matmul 768x768 fwd", steps=steps)

    # attention via plain XLA (chunk-free, bf16) for kernel comparison
    def xla_attn(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (Dh ** 0.5)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e9)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(jnp.bfloat16)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    _timed_op(xla_attn, (q[:2], k[:2], v[:2]), flops=flops / 8,
              name="xla attn fwd (B=2)", steps=steps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--part", default="all",
                    choices=("all", "pieces", "sweep", "remat", "kernels"))
    args = ap.parse_args()
    if args.part in ("all", "pieces"):
        print("== pieces (vocab 50257) ==")
        run_pieces(steps=args.steps)
        print("== pieces (vocab 50304) ==")
        run_pieces(steps=args.steps, vocab=50304)
    if args.part in ("all", "sweep"):
        print("== variants ==")
        run_variant("base v=50257 chunk=auto m=16", steps=args.steps)
        run_variant("v=50304 chunk=auto m=16", vocab=50304, steps=args.steps)
        run_variant("v=50304 chunk=4096 m=16", vocab=50304, ce_chunk=4096, steps=args.steps)
        run_variant("v=50304 chunk=8192 m=16", vocab=50304, ce_chunk=8192, steps=args.steps)
        run_variant("v=50304 dense-ce m=16", vocab=50304, ce_chunk=0, steps=args.steps)
        run_variant("v=50304 chunk=auto m=8", vocab=50304, micro=8, steps=args.steps)
    if args.part in ("all", "kernels"):
        print("== kernels ==")
        run_kernels()
    if args.part in ("all", "remat"):
        run_variant("v=50304 remat=off m=16", vocab=50304, remat=False, steps=args.steps)
        run_variant("v=50304 remat=off dense-ce m=16", vocab=50304, remat=False,
                    ce_chunk=0, steps=args.steps)
        run_variant("v=50304 remat=dots m=16", vocab=50304, remat=True,
                    remat_policy="dots", steps=args.steps)
        run_variant("v=50304 remat=off m=24", vocab=50304, remat=False, micro=24,
                    steps=args.steps)


if __name__ == "__main__":
    main()
