#!/usr/bin/env python
"""Perf-regression ledger over the committed bench trajectory.

The repo accumulates ``BENCH_rNN.json`` / ``MULTICHIP_rNN.json`` blocks
(one per growth rung — the runner's record of ``python bench.py`` and the
multichip dryrun sweep), but until this tool nothing READ the
trajectory: a perf regression only got caught if a human re-read old
JSON.  This turns the archive into a gate:

    python tools/perf_ledger.py                    # regressions, if any
    python tools/perf_ledger.py --all              # every trajectory row
    python tools/perf_ledger.py --check            # exit 1 on regression
    python tools/perf_ledger.py --json             # machine-readable
    python tools/perf_ledger.py --dir=/path        # ledgers elsewhere
    python tools/perf_ledger.py --tolerance=0.15   # global tolerance
    python tools/perf_ledger.py --tolerance=tokens_per_sec=0.05
    python tools/perf_ledger.py --profile-history=profile_history --check
    python tools/perf_ledger.py --selftest         # fixture must fail

``--profile-history=<dir>`` gates a CONTINUOUS-PROFILER ring instead of
the bench trajectory (docs/OBSERVABILITY.md "Continuous profiling"): the
newest two ``ds_prof_window_*.json`` window records are compared with
the profiler's own window differ — per-scope per-step device-seconds,
the same substring-matched ``--tolerance`` rules, lower-is-better — so
the on-disk history the live engine writes and the offline gate share
ONE tolerance contract.  ``--check`` exits 1 when any scope regressed.

What is parsed (keyed by the bench summary's block names — the same
tuple DSL004 pins as the ``summary_lines`` victim order):

- every BENCH block's ``parsed`` summary and/or the ``BENCH_JSON:`` line
  recovered from its ``tail``: the headline ``metric``/``value`` pair,
  ``vs_baseline``/``mfu``, and the named sub-blocks (``serving_metrics``,
  ``train_metrics``, ``overlap_ablation``, ``serving_prefix``,
  ``streamed_offload``, ``serving_host_tier``, ``fleet_chaos``,
  ``elastic_resume``, ``quant_comm``, ``pipe``) flattened to dotted
  numeric metrics;
- every MULTICHIP block's ``ok`` bit, ``n_devices``, and the per-recipe
  ``dryrun[name]: ... loss=X`` lines;
- each block's ``run_meta`` (git sha, jax/jaxlib, platform,
  ``schema_version`` — the bench.py ``run_metadata()`` stamp), kept so a
  regression across an ENVIRONMENT change is labeled as such instead of
  blamed on code.

Regression rule: per metric, compare the NEWEST point against the
previous one (the gate protects the tip of the trajectory; history is
context, not a verdict).  Direction comes from the metric name
(tokens/sec, speedup, mfu, goodput, ... are higher-better; latency, p99,
step_ms, bubble_share, loss, ... are lower-better; identity/shape fields
are neutral and never flagged).  A move beyond the tolerance (default
10%, configurable globally or per name-substring) is a named finding;
``--check`` exits nonzero when any exist.  Blocks that cannot be parsed
(e.g. a truncated tail) are REPORTED as gaps, never silently dropped.

Zero dependencies beyond the stdlib — no jax, no repo imports (dslint
DSL003 pins the closure); wired as ``make perf-diff`` and the
``--selftest`` runs in tier-1 next to the other jax-free tools.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the bench summary's droppable blocks — mirrors the summary_lines victim
# tuple that DSL004 pins in bench.py (plus run_meta, the metadata stamp)
SUMMARY_BLOCKS = ("serving_metrics", "train_metrics", "overlap_ablation",
                  "serving_prefix", "streamed_offload", "serving_host_tier",
                  "fleet_chaos", "elastic_resume", "quant_comm", "pipe",
                  "goodput")

# direction heuristics by name substring; NEUTRAL wins, then HIGHER,
# then LOWER; a name matching none is informational only
NEUTRAL = ("loss_parity", "token_identical", "exactly_once", "worlds",
           "world_save", "n_devices", "schema_version", "batch", "params",
           "seq", "new_tokens", "grad_accum", "steps", "demotes",
           "promotes", "restarts", "shed")
HIGHER = ("tokens_per_sec", "tok_s", "speedup", "mfu", "goodput",
          "retention", "hit_ratio", "compression", "savings",
          "vs_baseline", "bandwidth", "mbps", "ok", "_ratio")
LOWER = ("latency", "p99", "p50", "ttft", "step_ms", "ms_per_token",
         "bubble_share", "gap_share", "loss", "overhead_ms", "skew",
         "steps_to_recover", "resume_latency", "downtime")


def direction(name: str) -> Optional[str]:
    low = name.lower()
    for toks, d in ((NEUTRAL, None), (HIGHER, "higher"), (LOWER, "lower")):
        if any(t in low for t in toks):
            return d
    return None


def _flatten(prefix: str, obj, out: Dict[str, float]) -> None:
    """Numeric leaves of a summary block, dotted; non-numeric leaves and
    lists are attribution detail, not trajectory metrics."""
    if isinstance(obj, bool):
        out[prefix] = float(obj)
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)


def _summary_metrics(doc: dict) -> Tuple[Dict[str, float], Optional[dict]]:
    """One bench summary (or legacy record) document -> dotted metrics +
    its run_meta block (None for pre-schema blocks)."""
    out: Dict[str, float] = {}
    metric = doc.get("metric")
    if isinstance(metric, str) and isinstance(doc.get("value"),
                                              (int, float)):
        out[metric] = float(doc["value"])
    for k in ("vs_baseline", "mfu"):
        if isinstance(doc.get(k), (int, float)):
            out[k] = float(doc[k])
    for scope in (doc, doc.get("detail")
                  if isinstance(doc.get("detail"), dict) else {}):
        for blk in SUMMARY_BLOCKS:
            sub = scope.get(blk)
            if isinstance(sub, dict):
                _flatten(blk, sub, out)
    meta = doc.get("run_meta")
    return out, meta if isinstance(meta, dict) else None


_BENCH_JSON_RE = re.compile(r"BENCH_JSON: (\{.*\})")
_DRYRUN_RE = re.compile(r"dryrun\[([^\]]+)\][^\n]*?loss=([0-9.eE+-]+)")


def parse_bench_block(data: dict) -> Tuple[Dict[str, float],
                                           Optional[dict], bool]:
    """One ``BENCH_rNN.json``: metrics from the runner's ``parsed`` field
    and/or the ``BENCH_JSON:`` line recovered from the tail (the line
    wins where both name a metric — it is the bench's own summary).
    Returns ``(metrics, run_meta, parsed_ok)``."""
    metrics: Dict[str, float] = {}
    meta: Optional[dict] = None
    found = False
    docs = []
    if isinstance(data.get("parsed"), dict):
        docs.append(data["parsed"])
    m = _BENCH_JSON_RE.search(data.get("tail") or "")
    if m:
        try:
            docs.append(json.loads(m.group(1)))
        except ValueError:
            pass
    for doc in docs:
        got, dmeta = _summary_metrics(doc)
        if got:
            found = True
        metrics.update(got)
        meta = dmeta or meta
    return metrics, meta, found


def parse_multichip_block(data: dict) -> Tuple[Dict[str, float], bool]:
    """One ``MULTICHIP_rNN.json``: the sweep verdict plus per-recipe
    dryrun losses; a skipped sweep contributes nothing (and is not a
    parse gap)."""
    if data.get("skipped"):
        return {}, True
    out: Dict[str, float] = {"multichip.ok": float(bool(data.get("ok")))}
    if isinstance(data.get("n_devices"), (int, float)):
        out["multichip.n_devices"] = float(data["n_devices"])
    for name, loss in _DRYRUN_RE.findall(data.get("tail") or ""):
        try:
            out[f"multichip.dryrun.{name}.loss"] = float(loss)
        except ValueError:
            pass
    return out, True


def load_trajectory(ledger_dir: str) -> dict:
    """Every ledger block in ``ledger_dir`` -> per-metric trajectories.

    Returns ``{"points": {metric: [(run_key, value)...]},
    "meta": {run_key: run_meta}, "gaps": [run_key...], "runs": [...]}``
    with run keys like ``BENCH_r05`` ordered by family then rung."""
    points: Dict[str, List[Tuple[str, float]]] = {}
    meta: Dict[str, dict] = {}
    gaps: List[str] = []
    runs: List[str] = []
    paths = sorted(glob.glob(os.path.join(ledger_dir, "BENCH_*.json"))) \
        + sorted(glob.glob(os.path.join(ledger_dir, "MULTICHIP_*.json")))
    for path in paths:
        key = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            gaps.append(key)
            continue
        if key.startswith("MULTICHIP"):
            metrics, ok = parse_multichip_block(data)
        else:
            metrics, rmeta, ok = parse_bench_block(data)
            if rmeta is not None:
                meta[key] = rmeta
        runs.append(key)
        if not ok and not metrics:
            gaps.append(key)
        for name, value in metrics.items():
            points.setdefault(name, []).append((key, value))
    return {"points": points, "meta": meta, "gaps": gaps, "runs": runs}


def _tolerance_for(name: str, tolerances: List[Tuple[str, float]],
                   default: float) -> float:
    for sub, tol in tolerances:
        if sub in name:
            return tol
    return default


def find_regressions(traj: dict, default_tol: float = 0.10,
                     tolerances: Optional[List[Tuple[str, float]]] = None
                     ) -> List[dict]:
    """Tip-of-trajectory check: for every directional metric with >= 2
    points, flag a move beyond tolerance between the two NEWEST points.
    Findings name the block/metric, both runs, the relative move, and —
    when the two runs' ``run_meta`` stamps differ — the environment
    fields that changed (an env move is still reported, but attributable
    to the toolchain rather than the code)."""
    tolerances = tolerances or []
    findings = []
    for name, pts in sorted(traj["points"].items()):
        d = direction(name)
        if d is None or len(pts) < 2:
            continue
        (prev_run, prev), (last_run, last) = pts[-2], pts[-1]
        if prev == 0:
            continue
        rel = (last - prev) / abs(prev)
        tol = _tolerance_for(name, tolerances, default_tol)
        if (d == "higher" and rel < -tol) or (d == "lower" and rel > tol):
            f = {"metric": name, "direction": d,
                 "prev_run": prev_run, "prev": prev,
                 "last_run": last_run, "last": last,
                 "rel_change": round(rel, 4), "tolerance": tol}
            m0 = traj["meta"].get(prev_run) or {}
            m1 = traj["meta"].get(last_run) or {}
            env = sorted(k for k in set(m0) | set(m1)
                         if k != "git_sha" and m0.get(k) != m1.get(k))
            if env and (m0 or m1):
                f["env_changed"] = env
            findings.append(f)
    return findings


def render(traj: dict, findings: List[dict], show_all: bool) -> str:
    out = [f"perf ledger: {len(traj['runs'])} block(s), "
           f"{len(traj['points'])} metric trajectorie(s), "
           f"{len(findings)} regression(s)"]
    if traj["gaps"]:
        # no silent caps: a block the parser could not read is a HOLE in
        # the trajectory, and the gate must say so
        out.append("unparsed blocks (no metrics recovered): "
                   + ", ".join(traj["gaps"]))
    if show_all:
        for name, pts in sorted(traj["points"].items()):
            d = direction(name) or "-"
            vals = " ".join(f"{run.split('_')[-1]}={v:g}"
                            for run, v in pts)
            out.append(f"  [{d:>6}] {name}: {vals}")
    for f in findings:
        env = (f" [environment changed: {', '.join(f['env_changed'])}]"
               if f.get("env_changed") else "")
        out.append(
            f"REGRESSION {f['metric']}: {f['prev']:g} ({f['prev_run']}) "
            f"-> {f['last']:g} ({f['last_run']}), "
            f"{100 * f['rel_change']:+.1f}% vs {f['direction']}-is-better "
            f"tolerance {100 * f['tolerance']:.0f}%{env}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# selftest (tier-1 wired): clean trajectory passes, a seeded 20% tokens/s
# regression fails loudly
# ---------------------------------------------------------------------------


def _write_fixture(d: str, *, seeded: bool) -> None:
    def bench(n, value, p99, sha, jaxv):
        summary = {"metric": "demo_train_tokens_per_sec_per_chip",
                   "value": value, "unit": "tokens/sec",
                   "vs_baseline": value / 100.0, "mfu": 0.4,
                   "serving_metrics": {"tokens_per_sec": value / 2,
                                       "p99_latency_s": p99},
                   "run_meta": {"schema_version": 1, "git_sha": sha,
                                "jax": jaxv, "platform": "cpu"}}
        line = json.dumps(summary, separators=(",", ":"))
        block = {"n": n, "cmd": "python bench.py", "rc": 0,
                 "tail": f"noise\nBENCH_JSON: {line}\n{line}",
                 "parsed": summary}
        with open(os.path.join(d, f"BENCH_r{n:02d}.json"), "w") as fh:
            json.dump(block, fh)

    bench(1, 100.0, 0.20, "aaa", "0.4.1")
    bench(2, 110.0, 0.21, "bbb", "0.4.1")
    if seeded:
        # 20% tokens/s drop + a p99 blowup, across a jax version change
        bench(3, 88.0, 0.50, "ccc", "0.4.2")
    with open(os.path.join(d, "MULTICHIP_r01.json"), "w") as fh:
        json.dump({"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
                   "tail": "dryrun[zero3]: mesh={} loss=6.7719 step=1 OK"},
                  fh)
    with open(os.path.join(d, "MULTICHIP_r02.json"), "w") as fh:
        json.dump({"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
                   "tail": "dryrun[zero3]: mesh={} loss=6.7720 step=1 OK"},
                  fh)
    # a truncated block (the BENCH_r05 shape): reported as a gap
    with open(os.path.join(d, "BENCH_r04.json"), "w") as fh:
        json.dump({"n": 4, "cmd": "python bench.py", "rc": 0,
                   "tail": 'per_sec": 1190.4, "truncated...', "parsed": None},
                  fh)


def selftest() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="ds_perf_ledger_") as d:
        _write_fixture(d, seeded=False)
        traj = load_trajectory(d)
        clean = find_regressions(traj)
        assert clean == [], clean
        assert "BENCH_r04" in traj["gaps"], traj["gaps"]
        pts = traj["points"]["demo_train_tokens_per_sec_per_chip"]
        assert [v for _, v in pts] == [100.0, 110.0], pts
        assert traj["points"]["multichip.dryrun.zero3.loss"][0][1] == 6.7719
        text = render(traj, clean, show_all=True)
        assert "0 regression(s)" in text and "BENCH_r04" in text, text
    with tempfile.TemporaryDirectory(prefix="ds_perf_ledger_") as d:
        _write_fixture(d, seeded=True)
        traj = load_trajectory(d)
        bad = find_regressions(traj)
        names = {f["metric"] for f in bad}
        assert "demo_train_tokens_per_sec_per_chip" in names, bad
        assert "serving_metrics.p99_latency_s" in names, bad
        lead = [f for f in bad
                if f["metric"] == "demo_train_tokens_per_sec_per_chip"][0]
        assert lead["rel_change"] == -0.2 and lead["direction"] == "higher"
        # the jax bump between r02 and r03 is named, git_sha churn is not
        assert lead.get("env_changed") == ["jax"], lead
        text = render(traj, bad, show_all=False)
        assert "REGRESSION demo_train_tokens_per_sec_per_chip" in text
        assert "environment changed: jax" in text
        # a loose per-name tolerance can wave the same move through
        assert find_regressions(
            traj, tolerances=[("tokens_per_sec", 0.5),
                              ("vs_baseline", 0.5), ("p99", 2.0)]) == []
    print("perf_ledger selftest: OK")
    return 0


# ---------------------------------------------------------------------------
# --profile-history: gate a continuous-profiler ring with the profiler's
# own window differ (shared tolerance semantics)
# ---------------------------------------------------------------------------


def _load_continuous():
    """The continuous-profiler offline half, via trace_report's no-jax
    stub loader — ONE copy of the path-loading idiom in the toolchain."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    return trace_report.continuous


def profile_history_main(directory: str, flags: List[str],
                         default_tol: Optional[float],
                         tolerances: List[Tuple[str, float]]) -> int:
    continuous = _load_continuous()
    windows = continuous.HistoryRing(directory).latest(2)
    if len(windows) < 2:
        print(f"need >= 2 windows under {directory}, have {len(windows)}",
              file=sys.stderr)
        return 2
    prev, cur = windows[-2], windows[-1]
    regs = continuous.diff_windows(
        prev, cur,
        default_tol=(default_tol if default_tol is not None
                     else continuous.DEFAULT_TOLERANCE),
        tolerances=tolerances)
    if "--json" in flags:
        print(json.dumps({"prev_seq": prev.get("seq"),
                          "cur_seq": cur.get("seq"),
                          "regressions": regs}, sort_keys=True))
    else:
        print(f"profile history {directory}: window "
              f"#{prev.get('seq', '?')} -> #{cur.get('seq', '?')}, "
              f"{len(regs)} scope regression(s)")
        for r in regs:
            print(f"REGRESSION scope {r['scope']}: "
                  f"{r['prev_s']:g}s -> {r['cur_s']:g}s per step, "
                  f"{100 * r['rel']:+.1f}% vs lower-is-better tolerance "
                  f"{100 * r['tol']:.0f}%")
    if "--check" in flags and regs:
        return 1
    return 0


# ---------------------------------------------------------------------------


def main(argv: List[str]) -> int:
    flags = [a for a in argv[1:] if a.startswith("--")]
    if any(a for a in argv[1:] if not a.startswith("--")) \
            or "--help" in flags or "-h" in argv[1:]:
        print(__doc__.strip())
        return 0 if "--help" in flags or "-h" in argv[1:] else 2
    if "--selftest" in flags:
        return selftest()
    ledger_dir = _REPO
    default_tol: Optional[float] = None    # mode default when unset
    tolerances: List[Tuple[str, float]] = []
    profile_dir: Optional[str] = None
    for f in flags:
        if f.startswith("--dir="):
            ledger_dir = f.split("=", 1)[1]
        elif f.startswith("--profile-history="):
            profile_dir = f.split("=", 1)[1]
        elif f.startswith("--tolerance="):
            spec = f.split("=", 1)[1]
            name, sep, val = spec.rpartition("=")
            try:
                if sep:
                    tolerances.append((name, float(val)))
                else:
                    default_tol = float(val)
            except ValueError:
                print(f"bad tolerance: {spec}", file=sys.stderr)
                return 2
    if profile_dir is not None:
        return profile_history_main(profile_dir, flags, default_tol,
                                    tolerances)
    if default_tol is None:
        default_tol = 0.10
    traj = load_trajectory(ledger_dir)
    if not traj["runs"]:
        print(f"no BENCH_*/MULTICHIP_* ledgers under {ledger_dir}",
              file=sys.stderr)
        return 2
    findings = find_regressions(traj, default_tol, tolerances)
    if "--json" in flags:
        print(json.dumps({"runs": traj["runs"], "gaps": traj["gaps"],
                          "points": {k: [[r, v] for r, v in pts]
                                     for k, pts in traj["points"].items()},
                          "regressions": findings}, sort_keys=True))
    else:
        print(render(traj, findings, show_all="--all" in flags))
    if "--check" in flags and findings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
