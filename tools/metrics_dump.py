#!/usr/bin/env python
"""Pretty-print a metrics snapshot as a terminal table.

Sources (auto-detected from the one positional argument):

- a live ``/statz`` endpoint:   ``python tools/metrics_dump.py http://127.0.0.1:9100/statz``
  (a bare ``host:port`` or ``/metrics`` URL is normalized to ``/statz``)
- a saved snapshot file:        ``python tools/metrics_dump.py statz.json``
- a csvMonitor output dir:      ``python tools/metrics_dump.py ./csv_monitor/job``
  (one ``<event>.csv`` per series; the table shows each series' last value)

``--comms`` additionally prints the per-collective summary (count / bytes /
compression / p50 / p99 / busbw from the ``ds_comm_*`` family — the
training-side comm ledger, docs/OBSERVABILITY.md; ``compress`` = the
quantized transports' dense-equivalent-over-wire byte ratio, both series
recorded on one trace by comm/collectives_q.py) with the device-truth columns
(``ds_comm_<op>_device_seconds`` p50 + recomputed device busbw, when a
``/profilez``/watchdog capture populated them) alongside the analytic
attribution for side-by-side error reading, plus the offload-relay line
(bytes by direction / prefetch hit rate / stall, from ``ds_offload_*``)
when the offload path ran.  ``--serving`` prints the paged-KV pool
summary (pages used/free, cache-utilization percentiles, preemptions from
the ``ds_serve_kv_*`` / ``ds_serve_preempted_total`` series, the
prefix-cache hit-ratio line, and the KV host-tier line — resident /
demoted / promoted pages — when a host tier ran).  ``--requests``
prints the slowest-exemplar table from the same host's ``/requestz``
endpoint (or a saved ``/requestz`` snapshot file passed as the source):
per request id, latency, the queue/prefill/decode/preempted-wait phase
breakdown, preemption count and finish reason, plus the tail-attribution
line — the "which requests were slow and why" view.  ``--profile``
renders the latest continuous-profiler window (top scopes by per-step
device-seconds, run coverage %, capture overhead %) from the host's
``/profilez/history`` endpoint, a saved history snapshot, or a
``profile_history/`` ring directory (docs/OBSERVABILITY.md "Continuous
profiling").  ``ds_mem_*``
byte gauges render humanized (GiB/MiB) in the value column;
``ds_train_mfu`` and ``*_ratio`` histogram columns render as percentages.

Zero dependencies — stdlib only, same as the metrics layer it reads.
"""

from __future__ import annotations

import csv
import json
import os
import sys
from typing import Dict, List


def is_url(src: str) -> bool:
    return src.startswith(("http://", "https://")) or (
        ":" in src and not os.path.exists(src))


def base_url(src: str) -> str:
    """Normalize ``host[:port]`` or any known endpoint URL on the host to
    the server base (scheme + authority), stripping endpoint suffixes and
    any query/fragment — the ONE place the metrics server's URL shape is
    known (fleet_dump imports it too)."""
    url = src if src.startswith("http") else f"http://{src}"
    url = url.split("?", 1)[0].split("#", 1)[0].rstrip("/")
    for suffix in ("/metrics", "/statz", "/requestz", "/profilez/history",
                   "/profilez"):
        if url.endswith(suffix):
            url = url[: -len(suffix)]
    return url


def load_snapshot(src: str) -> Dict[str, object]:
    """Return the ``{name: value-or-dict}`` metrics mapping from a URL,
    JSON file, or csvMonitor directory."""
    if is_url(src):
        import urllib.request

        with urllib.request.urlopen(base_url(src) + "/statz",
                                    timeout=5) as resp:
            return json.load(resp)["metrics"]
    if os.path.isdir(src):
        out: Dict[str, object] = {}
        for fn in sorted(os.listdir(src)):
            if not fn.endswith(".csv"):
                continue
            with open(os.path.join(src, fn)) as fh:
                rows = list(csv.reader(fh))
            if len(rows) >= 2:       # header + at least one event
                step, value = rows[-1][0], rows[-1][1]
                out[fn[: -len(".csv")]] = {"last": float(value),
                                           "step": int(step),
                                           "events": len(rows) - 1}
        return out
    with open(src) as fh:
        data = json.load(fh)
    return data.get("metrics", data)     # accept bare or /statz-shaped


def human_bytes(n: float) -> str:
    for unit, scale in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(n) >= scale:
            return f"{n / scale:.2f} {unit}"
    return f"{int(n)} B"


def comms_rows(metrics: Dict[str, object]) -> List[List[str]]:
    """Per-collective summary rows [op, calls, bytes, compress, p50, p99,
    busbw, dev_p50, dev_busbw] from the ``ds_comm_*`` family (one row per
    op that recorded traffic).  ``compress`` is the per-op compression
    ratio (dense-equivalent bytes / wire bytes) for quantized transports —
    ``ds_comm_<op>_dense_bytes_total`` over ``ds_comm_<op>_bytes_total``,
    both recorded on the SAME trace by comm/collectives_q.py; dense ops
    leave it blank.  The device columns come from the device-truth
    ``ds_comm_<op>_device_*`` series (perfetto post-processor,
    docs/OBSERVABILITY.md "Device truth") and sit NEXT TO the analytic
    host-window attribution so the attribution error reads off one row."""

    def fam_sum(v) -> float:
        if isinstance(v, dict):             # {dtype=...} labeled family
            return sum(x for x in v.values() if isinstance(x, (int, float)))
        return float(v or 0)

    ops = {}
    for name in metrics:
        if name.startswith("ds_comm_") and name.endswith("_calls_total"):
            ops[name[len("ds_comm_"): -len("_calls_total")]] = None
        elif name.startswith("ds_comm_") and name.endswith("_device_seconds"):
            # a capture can populate device truth for an op the analytic
            # feed never counted (comms_logger off) — still a row
            v = metrics.get(name)
            if isinstance(v, dict) and v.get("count"):
                ops[name[len("ds_comm_"): -len("_device_seconds")]] = None
    rows = []
    for op in sorted(ops):
        calls = metrics.get(f"ds_comm_{op}_calls_total", 0)
        byt = fam_sum(metrics.get(f"ds_comm_{op}_bytes_total", 0))
        dense = fam_sum(metrics.get(f"ds_comm_{op}_dense_bytes_total", 0))
        dev = metrics.get(f"ds_comm_{op}_device_seconds") or {}
        if not calls and not byt and not (isinstance(dev, dict)
                                          and dev.get("count")):
            continue
        hist = metrics.get(f"ds_comm_{op}_seconds") or {}
        busbw = metrics.get(f"ds_comm_{op}_busbw_gbps", 0)
        if not isinstance(dev, dict):
            dev = {}
        dev_bw = metrics.get(f"ds_comm_{op}_device_busbw_gbps", 0)
        rows.append([op, str(calls), human_bytes(float(byt)),
                     f"{dense / byt:.2f}x" if dense and byt else "",
                     f"{hist.get('p50', 0):.6g}" if hist.get("count") else "",
                     f"{hist.get('p99', 0):.6g}" if hist.get("count") else "",
                     f"{busbw:.3g} GB/s" if busbw else "",
                     f"{dev.get('p50', 0):.6g}" if dev.get("count") else "",
                     f"{dev_bw:.3g} GB/s" if dev_bw else ""])
    return rows


def overlap_line(metrics: Dict[str, object]) -> str:
    """One-line compute/collective overlap indicator (the layer-chunked
    schedule, docs/OBSERVABILITY.md 'Overlap'): whether ``overlap_comm``
    was active on the scraped engine and how much comm a device capture
    measured hidden under compute."""
    def scalar(name):
        v = metrics.get(name)
        if isinstance(v, dict):             # csvMonitor series
            v = v.get("last")
        return v

    buckets = scalar("ds_overlap_buckets")
    if not buckets:
        return "overlap: off (GSPMD-placed collectives)"
    hidden = scalar("ds_overlap_hidden_comm_seconds_est") or 0.0
    line = f"overlap: on ({int(buckets)} buckets"
    if hidden:
        line += f", {hidden:.6g}s/step comm hidden under compute"
    elif scalar("ds_profile_window_seconds"):
        # a capture ran and measured zero hidden comm — the exact failure
        # being diagnosed; don't render it as "no capture"
        line += ", 0s comm hidden in last capture"
    else:
        line += ", no device capture yet"
    return line + ")"


def offload_relay_line(metrics: Dict[str, object]) -> str:
    """One-line offload host<->device relay summary from the
    ``ds_offload_*`` series (docs/OBSERVABILITY.md 'Training — offload
    streaming relay'); empty string when the offload path never ran."""
    fam = metrics.get("ds_offload_relay_bytes_total") or {}
    if not isinstance(fam, dict) or not fam:
        return ""
    h2d = float(fam.get('{dir="h2d"}', 0) or 0)
    d2h = float(fam.get('{dir="d2h"}', 0) or 0)
    if not (h2d or d2h):
        return ""
    hits = int(metrics.get("ds_offload_prefetch_hits_total", 0) or 0)
    misses = int(metrics.get("ds_offload_prefetch_misses_total", 0) or 0)
    stall = metrics.get("ds_offload_relay_seconds") or {}
    line = (f"offload relay: {human_bytes(h2d)} h2d / "
            f"{human_bytes(d2h)} d2h")
    if hits or misses:
        line += (f", prefetch {100 * hits / (hits + misses):.0f}% hit "
                 f"({hits}/{hits + misses})")
    if isinstance(stall, dict) and stall.get("count"):
        line += f", {stall['sum']:.4g}s stalled"
    return line


def render_comms(rows: List[List[str]]) -> str:
    header = ["collective", "calls", "bytes", "compress", "p50_s", "p99_s",
              "busbw", "dev_p50_s", "dev_busbw"]
    table = [header] + rows
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def serving_kv_summary(metrics: Dict[str, object]) -> str:
    """Paged-KV pool health lines from the ``ds_serve_kv_*`` series plus
    the prefix-cache line from ``ds_serve_prefix_*`` (docs/OBSERVABILITY.md
    'Serving — paged KV pool' / 'Serving — prefix cache')."""
    used = metrics.get("ds_serve_kv_pages_used")
    free = metrics.get("ds_serve_kv_pages_free")
    util = metrics.get("ds_serve_kv_cache_util_ratio") or {}
    pre = metrics.get("ds_serve_preempted_total", 0)
    if used is None and free is None and not util:
        return "(no ds_serve_kv_* series recorded)"
    lines = []
    if used is not None or free is not None:
        u, f = int(used or 0), int(free or 0)
        lines.append(f"kv pages: {u} used / {f} free ({u + f} total)")
    if isinstance(util, dict) and util.get("count"):
        lines.append("kv cache utilization: "
                     f"mean {100 * util['mean']:.1f}%  "
                     f"p50 {100 * util['p50']:.1f}%  "
                     f"p99 {100 * util['p99']:.1f}%  "
                     f"({util['count']} steps)")
    lines.append(f"preemptions: {int(pre)}")
    hit = float(metrics.get("ds_serve_prefix_hit_tokens_total", 0) or 0)
    miss = float(metrics.get("ds_serve_prefix_miss_tokens_total", 0) or 0)
    if hit or miss:
        cached = int(metrics.get("ds_serve_prefix_cache_pages", 0) or 0)
        ev = int(metrics.get("ds_serve_prefix_evictions_total", 0) or 0)
        lines.append(f"prefix cache: {100 * hit / (hit + miss):.1f}% hit "
                     f"ratio ({int(hit)} hit / {int(miss)} computed "
                     f"prefill tokens), {cached} cached pages, "
                     f"{ev} evictions")
    demote = int(metrics.get("ds_serve_kv_demote_total", 0) or 0)
    promote = int(metrics.get("ds_serve_kv_promote_total", 0) or 0)
    host = int(metrics.get("ds_serve_kv_host_pages", 0) or 0)
    if demote or promote or host:
        lines.append(f"kv host tier: {host} pages resident, "
                     f"{demote} demoted, {promote} promoted")
    ttft = metrics.get("ds_serve_ttft_seconds") or {}
    if isinstance(ttft, dict) and ttft.get("count"):
        lines.append(f"ttft: p50 {ttft['p50']:.4g}s  "
                     f"p99 {ttft['p99']:.4g}s  "
                     f"({int(ttft['count'])} requests)")
    # disaggregated-serving KV handoff (docs/RESILIENCE.md
    # "Disaggregated serving"): wire bytes by dtype vs the dense twin
    hand = metrics.get("ds_serve_kv_handoff_bytes_total") or {}
    if isinstance(hand, dict) and hand:
        dense = float(hand.get('{dtype="dense"}', 0) or 0)
        wire = sum(float(v or 0) for k, v in hand.items()
                   if k != '{dtype="dense"}')
        shipped = int(metrics.get("ds_serve_kv_handoff_pages_total", 0)
                      or 0)
        adopted = int(metrics.get("ds_serve_kv_adopted_pages_total", 0)
                      or 0)
        line = (f"kv handoff: {shipped} pages shipped / {adopted} "
                f"adopted, {human_bytes(wire)} on the wire")
        if dense:
            line += (f" ({human_bytes(dense)} dense twin, "
                     f"{100 * wire / dense:.0f}%)")
        lines.append(line)
    resumes = int(metrics.get("ds_serve_stream_resumes_total", 0) or 0)
    if resumes:
        lines.append(f"stream resumes: {resumes}")
    return "\n".join(lines)


def load_requestz(src: str) -> Dict[str, object]:
    """The ``/requestz`` snapshot from a live endpoint (any URL on the
    host is normalized to ``/requestz``) or a saved JSON file."""
    if is_url(src):
        import urllib.request

        with urllib.request.urlopen(base_url(src) + "/requestz",
                                    timeout=5) as resp:
            return json.load(resp)
    with open(src) as fh:
        return json.load(fh)


def load_profile_history(src: str) -> Dict[str, object]:
    """The ``/profilez/history`` snapshot from a live endpoint, a saved
    snapshot JSON, a single window file, or a ``profile_history/`` ring
    directory (read directly — the on-disk window files ARE the scrape
    payload, one JSON per window)."""
    if is_url(src):
        import urllib.request

        with urllib.request.urlopen(base_url(src) + "/profilez/history",
                                    timeout=5) as resp:
            return json.load(resp)
    if os.path.isdir(src):
        windows = []
        for fn in sorted(os.listdir(src)):
            if fn.startswith("ds_prof_window_") and fn.endswith(".json"):
                try:
                    with open(os.path.join(src, fn)) as fh:
                        windows.append(json.load(fh))
                except (OSError, ValueError):
                    pass         # pruned underneath us, or torn by a crash
        engines = sorted({w.get("engine") for w in windows
                          if w.get("engine")})
        return {"engines": engines, "windows": windows}
    with open(src) as fh:
        data = json.load(fh)
    if "windows" in data:
        return data
    return {"engines": [data.get("engine")] if data.get("engine") else [],
            "windows": [data]}          # a single saved window file


def profile_rows(window: Dict[str, object]) -> List[List[str]]:
    """Top-scope rows [scope, per_step_ms, share] for one window record,
    sorted by per-step device-seconds descending."""
    scopes = sorted((window.get("scopes") or {}).items(),
                    key=lambda kv: -kv[1])
    steps = window.get("steps") or 1
    wall = float(window.get("window_s") or 0.0) / max(1, steps)
    rows = []
    for name, sec in scopes:
        if sec <= 0.0:
            continue
        share = f"{100.0 * sec / wall:.1f}%" if wall else ""
        rows.append([name, f"{sec * 1e3:.4f}", share])
    return rows


def render_profile(snap: Dict[str, object]) -> str:
    """Latest-window view of a ``/profilez/history`` snapshot: one block
    per engine kind (a process can run both a training and a serving
    profiler), each with the coverage/overhead line and the top-scope
    table."""
    windows = snap.get("windows") or []
    if not windows:
        return ("(no continuous-profiler windows — is the profiler "
                "enabled? config continuous_profiler.enabled)")
    latest: Dict[str, Dict[str, object]] = {}
    for w in windows:                    # windows arrive oldest-first
        latest[str(w.get("engine"))] = w
    blocks = []
    for engine in sorted(latest):
        w = latest[engine]
        head = (f"engine={engine} window #{w.get('seq', '?')} "
                f"step={w.get('step')}: {w.get('steps')} step(s), "
                f"{float(w.get('window_s') or 0.0) * 1e3:.3f}ms wall, "
                f"device busy {100 * float(w.get('busy_ratio') or 0):.2f}%")
        lines = [head]
        if w.get("degraded"):
            lines.append("NOTE: degraded (host-range attribution only)")
        lines.append(
            f"run coverage {100 * float(w.get('coverage_ratio') or 0):.2f}%"
            f", capture overhead "
            f"{100 * float(w.get('overhead_ratio') or 0):.2f}%")
        rows = profile_rows(w)
        if rows:
            lines += render_table(["scope", "per_step_ms", "share"], rows)
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks)


def requests_rows(snap: Dict[str, object]) -> List[List[str]]:
    """Slowest-exemplar rows [id, latency, queue, prefill, decode,
    preempted_wait, toks, preempts, reason] from a ``/requestz``
    snapshot."""
    rows = []
    for rec in snap.get("slowest") or []:
        ph = rec.get("phases") or {}
        rows.append([str(rec["id"]), f"{rec['latency_s']:.4g}"]
                    + [f"{ph.get(p, 0.0):.4g}" for p in
                       ("queue", "prefill", "decode", "preempted_wait")]
                    + [str(rec.get("tokens_out", "")),
                       str(rec.get("preemptions", 0)),
                       str(rec.get("reason", ""))])
    return rows


def render_table(header: List[str], rows: List[List[str]]) -> List[str]:
    """Column-width-aligned table lines (header, separator, rows) — the
    one table renderer the ops tools share."""
    table = [header] + rows
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in table]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return lines


def render_requests(snap: Dict[str, object]) -> str:
    rows = requests_rows(snap)
    if not rows:
        return ("(no completed request timelines — is the tracer enabled? "
                "init_serving(request_trace=True))")
    header = ["id", "latency_s", "queue_s", "prefill_s", "decode_s",
              "preempt_wait_s", "toks", "preempts", "reason"]
    lines = [f"slowest {len(rows)} of {snap.get('completed_total', '?')} "
             f"completed ({snap.get('open', 0)} open)"]
    lines += render_table(header, rows)
    ta = snap.get("tail_attribution") or {}
    if ta.get("tail_n"):
        share = ta.get("phase_share") or {}
        parts = "  ".join(f"{p}={100 * share.get(p, 0.0):.1f}%"
                          for p in ("queue", "prefill", "decode",
                                    "preempted_wait"))
        lines.append(f"tail (>= p{int(100 * ta.get('p', 0.99))} cut "
                     f"{ta.get('cut_s', 0.0):.4g}s, n={ta['tail_n']}): "
                     f"dominant={ta.get('dominant_phase')}  {parts}")
    return "\n".join(lines)


def rows_from_snapshot(metrics: Dict[str, object]) -> List[List[str]]:
    """Flatten the snapshot into [name, count, mean, p50, p99, value]
    display rows (histograms fill the quantile columns, scalars the value
    column, labeled families one row per label set)."""
    rows = []

    def fmt(v):
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)

    def fmt_scalar(name, v):
        if name.endswith("_bytes") and isinstance(v, (int, float)) and v:
            return f"{fmt(v)} ({human_bytes(float(v))})"
        if name == "ds_train_mfu" and isinstance(v, (int, float)) and v:
            return f"{fmt(v)} ({100 * v:.2f}%)"
        return fmt(v)

    def emit(name, v):
        if isinstance(v, dict) and "p50" in v:          # histogram
            if name.endswith("_ratio"):                 # fractions -> %
                rows.append([name, str(v["count"]),
                             f"{100 * v['mean']:.1f}%",
                             f"{100 * v['p50']:.1f}%",
                             f"{100 * v['p99']:.1f}%", ""])
                return
            rows.append([name, str(v["count"]), fmt(v["mean"]),
                         fmt(v["p50"]), fmt(v["p99"]), ""])
        elif isinstance(v, dict) and "last" in v:       # csvMonitor series
            rows.append([name, str(v["events"]), "", "", "",
                         f"{fmt(v['last'])} @ step {v['step']}"])
        elif isinstance(v, dict):                       # labeled family
            for labels, sub in sorted(v.items()):
                emit(f"{name}{labels}", sub)
        else:
            rows.append([name, "", "", "", "", fmt_scalar(name, v)])

    for name, v in sorted(metrics.items()):
        emit(name, v)
    return rows


def render(rows: List[List[str]]) -> str:
    return "\n".join(render_table(
        ["metric", "count", "mean", "p50", "p99", "value"], rows))


def main(argv: List[str]) -> int:
    args = [a for a in argv[1:] if not a.startswith("--")]
    flags = {a for a in argv[1:] if a.startswith("--")}
    if len(args) != 1 or "--help" in flags or "-h" in argv[1:]:
        print(__doc__.strip())
        return 0 if len(args) == 1 else 2
    if "--requests" in flags:
        # the source here is the /requestz surface (a URL is normalized to
        # it; a file is a saved /requestz snapshot), not a /statz snapshot
        print(render_requests(load_requestz(args[0])))
        return 0
    if "--profile" in flags:
        # likewise the /profilez/history surface: a URL normalizes to it,
        # a directory is the on-disk profile_history/ ring itself
        print(render_profile(load_profile_history(args[0])))
        return 0
    metrics = load_snapshot(args[0])
    if not metrics:
        print("(no metrics found)")
        return 1
    print(render(rows_from_snapshot(metrics)))
    if "--comms" in flags:
        rows = comms_rows(metrics)
        print()
        print(render_comms(rows) if rows
              else "(no ds_comm_* traffic recorded)")
        print(overlap_line(metrics))
        relay = offload_relay_line(metrics)
        if relay:
            print(relay)
    if "--serving" in flags:
        print()
        print(serving_kv_summary(metrics))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
