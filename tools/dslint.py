#!/usr/bin/env python
"""dslint — AST-level invariant checker for this repo's incident-derived
correctness rules (donation safety, sync-free hot paths, jax-free tools,
telemetry contracts).  See docs/LINT.md for the rule catalogue and the
suppression syntax.

    python tools/dslint.py                          # lint the default set
    python tools/dslint.py deepspeed_tpu tools bench.py
    python tools/dslint.py --json                   # machine-readable
    python tools/dslint.py --rules DSL003,DSL004    # subset
    python tools/dslint.py --list-rules
    python tools/dslint.py --selftest               # seeded fixtures

Exit codes: 0 clean, 1 findings, 2 usage/selftest failure.

Zero dependencies beyond the stdlib — **no jax import**.  The analyzer
package (``deepspeed_tpu/analysis``) is loaded by FILE PATH (the
fleet_dump/ckpt_verify idiom) so importing it never executes the
jax-pulling ``deepspeed_tpu/__init__``; rule DSL003 checks this tool's
own closure along with the other operator tools.
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# literal so the DSL003 resolver can follow this loader statically
_ANALYSIS_INIT = os.path.join("deepspeed_tpu", "analysis", "__init__.py")

DEFAULT_PATHS = ("deepspeed_tpu", "tools", "bench.py")


def _load_analysis():
    """The analysis package: reuse it when the repo package is already
    imported (in-process test callers), else load by file path under a
    private name so no jax-importing ``__init__`` runs."""
    mod = sys.modules.get("deepspeed_tpu.analysis")
    if mod is not None:
        return mod
    mod = sys.modules.get("_ds_analysis")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(_REPO, _ANALYSIS_INIT)
    spec = importlib.util.spec_from_file_location(
        "_ds_analysis", path,
        submodule_search_locations=[os.path.dirname(path)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv) -> int:
    args = list(argv[1:])
    as_json = "--json" in args
    verbose = "--verbose" in args
    for flag in ("--json", "--verbose"):
        while flag in args:
            args.remove(flag)
    rule_filter = None
    if "--rules" in args:
        i = args.index("--rules")
        try:
            rule_filter = {r.strip() for r in args[i + 1].split(",")
                           if r.strip()}
        except IndexError:
            print("dslint: --rules needs a comma-separated id list",
                  file=sys.stderr)
            return 2
        del args[i:i + 2]

    analysis = _load_analysis()

    if "--list-rules" in args:
        for rule in analysis.RULES:
            print(f"{rule.id}  {rule.title}")
        return 0

    if "--selftest" in args:
        failures = analysis.run_selftest(verbose=verbose)
        if failures:
            for f in failures:
                print(f"dslint selftest FAILED: {f}", file=sys.stderr)
            return 2
        # the operator-box contract this tool documents (standalone runs
        # only — in-process tier-1 callers already carry jax)
        if os.path.basename(sys.argv[0]).startswith("dslint"):
            assert "jax" not in sys.modules, "tools/dslint.py imported jax"
        print("dslint selftest: OK "
              f"({len(analysis.RULES)} rules + suppression machinery)")
        return 0

    paths = args or [os.path.join(_REPO, p) for p in DEFAULT_PATHS]
    rules = analysis.RULES
    if rule_filter is not None:
        unknown = rule_filter - analysis.rule_ids()
        if unknown:
            print(f"dslint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in analysis.RULES if r.id in rule_filter]
    try:
        findings, project = analysis.run_paths(paths, root=_REPO,
                                               rules=rules)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 2

    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    if as_json:
        print(json.dumps({
            "version": 1,
            "root": project.root,
            "files": len(project.files),
            "rules": sorted(r.id for r in rules),
            "findings": [f.to_dict() for f in findings],
            "counts": counts,
            "ok": not findings,
        }, indent=None, separators=(",", ":"), sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"dslint: {len(project.files)} files, {n} finding"
              f"{'' if n == 1 else 's'}"
              + (f" ({', '.join(f'{k}={v}' for k, v in sorted(counts.items()))})"
                 if counts else ""))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
