"""Quantized-collective benchmark rung (slow): dense vs int8 per
collective family on the 8-device CPU mesh (``bench.bench_quant_comm``).
Marked ``slow`` — the fast tier-1 coverage is
``tests/unit/test_collectives_q.py`` / ``test_qcomm_engine.py``.  On CPU
the bytes + compression + loss-parity acceptance bits are exact
(backend-independent); the throughput ratio is a TPU row."""

import pytest

pytestmark = pytest.mark.slow


def test_quant_comm_bench_scenario(capsys):
    from bench import bench_quant_comm

    out = bench_quant_comm(steps=2, warmup=1)
    assert out["status"] == "ok", out
    # the ROADMAP item 2 acceptance: every opted-in collective moves
    # ~2-4x fewer bytes than its dense twin ON THE SAME TRACE
    for op in ("q_all_reduce", "q_all_gather", "q_reduce_scatter"):
        assert 2.0 <= out["compression"][op] <= 4.5, (op, out["compression"])
    assert out["loss_parity"] == {"all_reduce": True, "gather_rs": True}
    for fam, row in out["families"].items():
        assert row["dense"]["tokens_per_sec"] > 0
        assert row["int8"]["tokens_per_sec"] > 0
    with capsys.disabled():
        print(f"\nquant comm bench (CPU): compression {out['compression']}, "
              f"parity {out['loss_parity']}")
