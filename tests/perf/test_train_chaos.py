"""Training chaos matrix (slow; ``make chaos``): the ISSUE 14 elastic
resilience scenarios at larger-than-tier-1 scale — the
``bench_elastic_resume`` rung, a randomized kill-at-byte sweep across an
elastic save/resume cycle, and a multi-round gradient-bomb campaign with
world changes between rounds.  The fast tier-1 chaos coverage lives in
``tests/unit/test_elastic_train.py`` / ``test_anomaly.py`` /
``test_resilience.py``."""

import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.runtime.checkpoint_engine import atomic
from deepspeed_tpu.testing import chaos
from tests.unit.simple_model import SimpleModel, random_dataset

pytestmark = pytest.mark.slow

X, Y = random_dataset(n=64)
TBS = 8


def _engine(devs, gas, save_dir=None, stage=2):
    mesh = build_mesh(devices=jax.devices()[:devs])
    set_global_mesh(mesh)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": stage},
           "steps_per_print": 10**9}
    if save_dir is not None:
        cfg["anomaly_detection"] = {"enabled": True, "factor": 6.0,
                                    "window": 16, "warmup": 3,
                                    "patience": 2, "save_dir": save_dir}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, mesh=mesh,
        rng=jax.random.PRNGKey(3))
    return engine


def _steps(engine, n, start=0):
    for i in range(start, start + n):
        gas = engine.config.gradient_accumulation_steps
        per = TBS // gas
        for g in range(gas):
            lo = ((i % 4) * TBS + g * per) % 56
            engine.forward((X[lo:lo + per], Y[lo:lo + per]))
        engine.step()


def test_elastic_resume_bench_scenario(capsys):
    from bench import bench_elastic_resume

    out = bench_elastic_resume(tiny=True)
    assert out["status"] == "ok", out
    assert out["loss_parity"] is True
    assert out["steps_to_recover_max"] == 0, \
        "the first post-resume step should already track the trajectory"
    assert out["resume_latency_s_max"] > 0
    assert set(out["resumes"]) == {str(w) for w in out["worlds"]}
    with capsys.disabled():
        print(f"\nelastic resume bench (tiny/CPU): save@{out['world_save']}"
              f" -> {out['worlds']}, resume latency max "
              f"{out['resume_latency_s_max']}s, steps-to-recover "
              f"{out['steps_to_recover_max']}, parity {out['loss_parity']}")


def test_chaos_matrix_random_kill_sweep_elastic_cycle(tmp_path):
    """Randomized kill-at-byte sweep ACROSS world changes: every crashed
    save leaves the previous tag loadable, and each survivor resumes at
    a DIFFERENT world (4 -> 2 -> 8 -> 4) with the trajectory intact."""
    rng = np.random.default_rng(11)
    save_dir = str(tmp_path)
    worlds = [4, 2, 8, 4]
    e = _engine(worlds[0], gas=2)
    _steps(e, 2)
    e.save_checkpoint(save_dir, tag="gen0")
    prev_tag = "gen0"
    for gen, devs in enumerate(worlds[1:], start=1):
        # a crashed save at a random byte offset leaves debris only
        total = sum(os.path.getsize(os.path.join(root, f))
                    for root, _d, fs in os.walk(os.path.join(save_dir,
                                                             prev_tag))
                    for f in fs)
        with pytest.raises(chaos.InjectedFault):
            with chaos.crash_on_write(int(rng.integers(0, total)), save_dir):
                e.save_checkpoint(save_dir, tag=f"crash{gen}")
        assert atomic.read_latest(save_dir) == prev_tag
        # the next incarnation comes up at a different world and resumes
        e = _engine(devs, gas=2)
        e.forward((X[:devs], Y[:devs]))
        ckpt_dir, _ = e.load_checkpoint(save_dir)
        assert ckpt_dir is not None and ckpt_dir.endswith(prev_tag)
        assert e.config.train_batch_size == TBS
        _steps(e, 2, start=2 * gen)
        tag = f"gen{gen}"
        e.save_checkpoint(save_dir, tag=tag)
        assert atomic.verify_dir(os.path.join(save_dir, tag),
                                 level="full").ok
        assert atomic.deep_verify(os.path.join(save_dir, tag)) == []
        prev_tag = tag


def test_chaos_matrix_bomb_rounds_with_world_change(tmp_path):
    """Multi-round gradient-bomb campaign: each round bombs past the
    patience threshold, the ladder rolls back, training re-converges,
    and the NEXT round runs at a different world size off the same
    checkpoint chain."""
    save_dir = str(tmp_path)
    for round_idx, devs in enumerate((4, 2)):
        e = _engine(devs, gas=2, save_dir=save_dir)
        if round_idx == 0:
            _steps(e, 4)
        else:
            e.forward((X[:devs], Y[:devs]))
            ckpt_dir, _ = e.load_checkpoint(save_dir)
            assert ckpt_dir is not None
            _steps(e, 2, start=4)
        e.save_checkpoint(save_dir, tag=f"good{round_idx}")
        p0 = jax.tree.map(lambda a: np.array(a),
                          jax.device_get(e.state.params))
        with chaos.gradient_bomb(e, scale=1e18, on_call=1, n=6):
            _steps(e, 3, start=10)
        # contained: params equal the round's good tag
        for a, b in zip(jax.tree.leaves(p0),
                        jax.tree.leaves(jax.device_get(e.state.params))):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b, np.float32))
        assert e._anomaly.rollbacks >= 1
        _steps(e, 2, start=20)          # re-converges post-rollback
        assert e._anomaly.consecutive == 0
