"""Disaggregated-serving benchmark rung (slow): the shared-prefix trace
through the router over a monolithic and a role-split fleet, plain and
streaming (``bench.bench_disagg_serving``).  Marked ``slow`` — outside
tier-1; the fast tier-1 coverage is tests/unit/test_disagg_serving.py.
On the CPU mesh this validates the grid mechanics and the
token-identity / wire-compression / TTFT-before-completion acceptance
bits; the goodput-ratio number is a TPU row."""

import pytest

pytestmark = pytest.mark.slow


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_disagg_serving_bench_scenario(capsys):
    from bench import bench_disagg_serving

    out = bench_disagg_serving(num_requests=8, num_slots=4, tiny=True)
    # the acceptance bits: greedy outputs identical across the whole
    # role-split x streaming grid, int8 wire strictly under the dense
    # twin, and the first streamed chunk landing before completion
    assert out["outputs_token_identical"] is True
    assert 0 < out["handoff_wire_bytes"] < out["handoff_dense_bytes"]
    assert out["handoff_compression"] > 1.0
    assert 0 < out["ttft_stream_over_total"] < 1.0
    for side in ("mono", "disagg"):
        for variant in ("plain", "stream"):
            cell = out[side][variant]
            assert cell["answered"] == 8, (side, variant, cell)
            assert cell["token_identical"] is True
            assert cell["goodput_tok_s"] > 0
    # only the role-split fleet ships pages
    assert out["disagg"]["plain"]["handoff_pages_shipped"] > 0
    assert "handoff_pages_shipped" not in out["mono"]["plain"]
    with capsys.disabled():
        print(f"\ndisagg serving bench (tiny/CPU): goodput ratio "
              f"{out['disagg_goodput_ratio']}x, stream TTFT/total "
              f"{out['ttft_stream_over_total']}, handoff compression "
              f"{out['handoff_compression']}x "
              f"({out['handoff_wire_bytes']}B wire / "
              f"{out['handoff_dense_bytes']}B dense)")
