"""Paged-KV fragmentation stress (slow — excluded from tier-1): many
short chat-like requests churning against one long request through an
oversubscribed page pool.  Slots turn over constantly, pages free and
re-allocate out of order (the free list interleaves short- and long-lived
requests), and the long request is preempted and resumed under pressure —
token parity against sequential ``generate()`` plus the allocator leak
probe after every wave is the acceptance bar."""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm

pytestmark = pytest.mark.slow


def test_paged_fragmentation_churn(devices, rng):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    params = model.init(rng, np.zeros((1, 8), np.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    # 4 slots x 4-page windows would want 16 pages; give the pool 8 — two
    # concurrently-maturing long requests alone fill it, so churn MUST
    # preempt under load
    serve = deepspeed_tpu.init_serving(
        model, config={"dtype": "float32", "max_out_tokens": 64,
                       "kv_page_tokens": 16, "kv_pool_tokens": 128},
        num_slots=4, prefill_chunk=8, decode_block_tokens=3)
    serve.set_params(params)
    assert serve.pool.num_pages == 9

    rng_np = np.random.default_rng(0)
    total_preempts = 0
    for wave in range(3):
        prompts = [np.asarray(rng_np.integers(0, 256, size=int(n)),
                              np.int32)
                   for n in rng_np.integers(3, 14, size=9)]
        news = [int(n) for n in rng_np.integers(2, 9, size=9)]
        # two long requests per wave, submitted FIRST so they mature
        # together: prompt + output spans the full 4-page window each
        for _ in range(2):
            prompts.insert(0, np.asarray(rng_np.integers(0, 256, size=12),
                                         np.int32))
            news.insert(0, 48)
        want = [np.asarray(ref.generate(p[None], max_new_tokens=n,
                                        do_sample=False))[0, len(p):]
                for p, n in zip(prompts, news)]
        reqs = [serve.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        serve.run()
        for i, (req, w) in enumerate(zip(reqs, want)):
            np.testing.assert_array_equal(
                np.asarray(req.output_tokens), w,
                err_msg=f"wave {wave} request {i} diverged under churn")
        assert serve.pool.pages_used == 0
        serve.pool.check_no_leak()
        serve.scheduler.drain_finished()
        total_preempts += sum(r.preemptions for r in reqs)
    # pressure was real: a 9-page pool cannot hold 4 full windows, so the
    # churn must have cycled through preempt-resume at least once
    assert total_preempts >= 1
