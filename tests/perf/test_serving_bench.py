"""Serving benchmark scenario (slow): Poisson arrivals, mixed lengths,
continuous batching vs the static-batch baseline at equal slot count.
Marked ``slow`` — excluded from tier-1; the fast tier-1 serving coverage is
``tests/unit/test_serving.py``.  On the CPU mesh this validates the
scenario mechanics and reports the measured speedup; the ≥2x goodput
acceptance target is for the 125M config on real TPU (``bench.py``)."""

import pytest

pytestmark = pytest.mark.slow


def test_serving_bench_scenario(capsys):
    from bench import bench_serving

    out = bench_serving(num_requests=12, num_slots=4, qps=200.0, tiny=True)
    for side in ("continuous", "fixed_slot", "static"):
        assert out[side]["goodput_tok_s"] > 0
        assert out[side]["p99_latency_s"] >= out[side]["p50_latency_s"]
    assert (out["continuous"]["tokens"] == out["static"]["tokens"]
            == out["fixed_slot"]["tokens"]), \
        "goodput must count the same requested tokens on every side"
    assert out["goodput_speedup"] > 0
    assert out["paged_vs_fixed_speedup"] > 0
    # equal-HBM comparison: the paged side runs 2x slots on the same KV
    # budget, and allocation-on-demand makes its cache utilization at
    # least the fixed reservation's on the identical trace
    assert out["continuous"]["slots"] == 2 * out["fixed_slot"]["slots"]
    assert out["continuous"]["kv_util"] >= out["fixed_slot"]["kv_util"] > 0
    # serving-health sub-object (BENCH_r*.json rows track these)
    m = out["metrics"]
    assert m["ttft_p99_s"] >= m["ttft_p50_s"] > 0
    assert m["queue_wait_p99_s"] >= 0
    assert 0 < m["mean_slot_occupancy"] <= 1
    assert 0 < m["kv_util"] <= 1
    assert m["preemptions"] >= 0
    assert m["pages"]["pool"] * m["pages"]["page_tokens"] >= \
        m["pages"]["budget_tokens"]
    with capsys.disabled():
        print(f"\nserving bench (tiny/CPU): paged "
              f"{out['continuous']['goodput_tok_s']} tok/s vs fixed-slot "
              f"{out['fixed_slot']['goodput_tok_s']} tok/s "
              f"({out['paged_vs_fixed_speedup']}x at equal KV HBM, util "
              f"{out['continuous']['kv_util']} vs "
              f"{out['fixed_slot']['kv_util']}) vs static "
              f"{out['static']['goodput_tok_s']} tok/s "
              f"({out['goodput_speedup']}x); p99 "
              f"{out['continuous']['p99_latency_s']}s vs "
              f"{out['static']['p99_latency_s']}s")


def test_prefix_serving_bench_scenario(capsys):
    """Shared-prefix scenario (bench_prefix_serving): the tentpole
    acceptance pair at tiny/CPU scale — prefill tokens computed drop
    >= 40% with prefix caching on, and outputs are token-identical to
    the cache-off run of the identical trace."""
    from bench import bench_prefix_serving

    out = bench_prefix_serving(num_requests=16, num_slots=4, qps=200.0,
                               tiny=True)
    for side in ("cache_on", "cache_off"):
        assert out[side]["goodput_tok_s"] > 0
        assert out[side]["prefill_tokens_computed"] > 0
    # identical trace, identical tokens delivered on both sides
    assert out["cache_on"]["tokens"] == out["cache_off"]["tokens"]
    assert out["outputs_token_identical"] is True
    # the acceptance floor: >= 40% of prefill compute skipped
    assert out["prefill_savings_ratio"] >= 0.40, out["prefill_savings_ratio"]
    assert 0 < out["prefix_hit_ratio"] <= 1
    assert out["cache_on"]["prefix_cache_pages"] > 0
    with capsys.disabled():
        print(f"\nprefix-caching bench (tiny/CPU): prefill "
              f"{out['cache_on']['prefill_tokens_computed']} vs "
              f"{out['cache_off']['prefill_tokens_computed']} tokens "
              f"computed ({100 * out['prefill_savings_ratio']:.0f}% saved, "
              f"hit ratio {out['prefix_hit_ratio']}), goodput "
              f"{out['prefix_goodput_speedup']}x, outputs identical: "
              f"{out['outputs_token_identical']}")
