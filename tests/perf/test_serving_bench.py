"""Serving benchmark scenario (slow): Poisson arrivals, mixed lengths,
continuous batching vs the static-batch baseline at equal slot count.
Marked ``slow`` — excluded from tier-1; the fast tier-1 serving coverage is
``tests/unit/test_serving.py``.  On the CPU mesh this validates the
scenario mechanics and reports the measured speedup; the ≥2x goodput
acceptance target is for the 125M config on real TPU (``bench.py``)."""

import pytest

pytestmark = pytest.mark.slow


def test_serving_bench_scenario(capsys):
    from bench import bench_serving

    out = bench_serving(num_requests=12, num_slots=4, qps=200.0, tiny=True)
    for side in ("continuous", "fixed_slot", "static"):
        assert out[side]["goodput_tok_s"] > 0
        assert out[side]["p99_latency_s"] >= out[side]["p50_latency_s"]
    assert (out["continuous"]["tokens"] == out["static"]["tokens"]
            == out["fixed_slot"]["tokens"]), \
        "goodput must count the same requested tokens on every side"
    assert out["goodput_speedup"] > 0
    assert out["paged_vs_fixed_speedup"] > 0
    # equal-HBM comparison: the paged side runs 2x slots on the same KV
    # budget, and allocation-on-demand makes its cache utilization at
    # least the fixed reservation's on the identical trace
    assert out["continuous"]["slots"] == 2 * out["fixed_slot"]["slots"]
    assert out["continuous"]["kv_util"] >= out["fixed_slot"]["kv_util"] > 0
    # serving-health sub-object (BENCH_r*.json rows track these)
    m = out["metrics"]
    assert m["ttft_p99_s"] >= m["ttft_p50_s"] > 0
    assert m["queue_wait_p99_s"] >= 0
    assert 0 < m["mean_slot_occupancy"] <= 1
    assert 0 < m["kv_util"] <= 1
    assert m["preemptions"] >= 0
    assert m["pages"]["pool"] * m["pages"]["page_tokens"] >= \
        m["pages"]["budget_tokens"]
    with capsys.disabled():
        print(f"\nserving bench (tiny/CPU): paged "
              f"{out['continuous']['goodput_tok_s']} tok/s vs fixed-slot "
              f"{out['fixed_slot']['goodput_tok_s']} tok/s "
              f"({out['paged_vs_fixed_speedup']}x at equal KV HBM, util "
              f"{out['continuous']['kv_util']} vs "
              f"{out['fixed_slot']['kv_util']}) vs static "
              f"{out['static']['goodput_tok_s']} tok/s "
              f"({out['goodput_speedup']}x); p99 "
              f"{out['continuous']['p99_latency_s']}s vs "
              f"{out['static']['p99_latency_s']}s")


def test_prefix_serving_bench_scenario(capsys):
    """Shared-prefix scenario (bench_prefix_serving): the tentpole
    acceptance pair at tiny/CPU scale — prefill tokens computed drop
    >= 40% with prefix caching on, and outputs are token-identical to
    the cache-off run of the identical trace."""
    from bench import bench_prefix_serving

    out = bench_prefix_serving(num_requests=16, num_slots=4, qps=200.0,
                               tiny=True)
    for side in ("cache_on", "cache_off"):
        assert out[side]["goodput_tok_s"] > 0
        assert out[side]["prefill_tokens_computed"] > 0
    # identical trace, identical tokens delivered on both sides
    assert out["cache_on"]["tokens"] == out["cache_off"]["tokens"]
    assert out["outputs_token_identical"] is True
    # the acceptance floor: >= 40% of prefill compute skipped
    assert out["prefill_savings_ratio"] >= 0.40, out["prefill_savings_ratio"]
    assert 0 < out["prefix_hit_ratio"] <= 1
    assert out["cache_on"]["prefix_cache_pages"] > 0
    with capsys.disabled():
        print(f"\nprefix-caching bench (tiny/CPU): prefill "
              f"{out['cache_on']['prefill_tokens_computed']} vs "
              f"{out['cache_off']['prefill_tokens_computed']} tokens "
              f"computed ({100 * out['prefill_savings_ratio']:.0f}% saved, "
              f"hit ratio {out['prefix_hit_ratio']}), goodput "
              f"{out['prefix_goodput_speedup']}x, outputs identical: "
              f"{out['outputs_token_identical']}")


def test_host_tier_bench_scenario(capsys):
    """KV-host-tier thrash scenario (bench_host_tier_serving): at a pool
    that always evicts cached history, tier-on shows a strictly higher
    prefix hit ratio with token-identical outputs (ISSUE 11 acceptance
    pair at tiny/CPU scale)."""
    from bench import bench_host_tier_serving

    out = bench_host_tier_serving(num_requests=14, num_slots=2, qps=200.0,
                                  tiny=True)
    assert out["outputs_token_identical"] is True
    assert out["hit_ratio_on"] > out["hit_ratio_off"], out
    assert out["demotes"] > 0 and out["promotes"] > 0
    assert out["tier_off"]["demotes"] == 0
    # fewer prefill tokens actually computed with the tier on
    assert (out["tier_on"]["prefill_tokens_computed"]
            < out["tier_off"]["prefill_tokens_computed"])
    with capsys.disabled():
        print(f"\nkv-host-tier bench (tiny/CPU): hit ratio "
              f"{out['hit_ratio_on']} (tier on) vs {out['hit_ratio_off']} "
              f"(off), {out['demotes']} demotes / {out['promotes']} "
              f"promotes, outputs identical: "
              f"{out['outputs_token_identical']}")


def test_streamed_rung_scenario(capsys):
    """Streamed-offload relay ablation (bench_streamed_rung) at tiny/CPU
    scale: int8 relay ships measurably fewer H2D bytes, prefetch hits
    register, and the loss stays within the parity bound of the plain
    (non-offloaded) engine."""
    from bench import bench_streamed_rung

    out = bench_streamed_rung(steps=2, warmup=1, tiny=True)
    assert out["status"] == "ok", out
    assert out["relay_bytes_ratio"] > 1.3, out["relay_bytes_ratio"]
    assert out["loss_parity"] is True
    for side in ("bf16", "int8"):
        assert out[side]["tokens_per_sec"] > 0
        assert out[side]["prefetch_hits"] > 0
        assert out[side]["h2d_bytes_per_step"] > 0
        assert out[side]["d2h_bytes_per_step"] > 0
    assert (out["int8"]["h2d_bytes_per_step"]
            < out["bf16"]["h2d_bytes_per_step"])
    with capsys.disabled():
        print(f"\nstreamed-offload bench (tiny/CPU): relay bytes ratio "
              f"{out['relay_bytes_ratio']}x (bf16 {out['bf16']['relay_MBps']}"
              f" MB/s vs int8 {out['int8']['relay_MBps']} MB/s), speedup "
              f"{out['streamed_speedup']}x (relay-bound only on TPU), "
              f"loss parity: {out['loss_parity']}")
