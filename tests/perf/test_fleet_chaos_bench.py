"""Fleet chaos benchmark rung (slow): the bimodal trace through the
router over two replicas, clean vs replica-kill+supervisor-restart
mid-trace (``bench.bench_fleet_chaos``).  Marked ``slow`` — runs under
``make chaos``, outside tier-1; the fast tier-1 chaos coverage is
``tests/unit/test_serving_chaos.py``.  On the CPU mesh this validates
the scenario mechanics and the exactly-once/token-identity acceptance
bits; the goodput-retention number is a TPU row."""

import pytest

pytestmark = pytest.mark.slow


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_fleet_chaos_bench_scenario(capsys):
    from bench import bench_fleet_chaos

    out = bench_fleet_chaos(num_requests=12, tiny=True)
    # the acceptance bits: zero drops / zero duplicates / greedy outputs
    # unchanged, on BOTH sides — and the chaos side really was chaotic
    assert out["answered_exactly_once"] is True
    assert out["outputs_token_identical"] is True
    assert out["restarts_observed"] >= 1, \
        "the kill+restart never happened; the chaos side measured nothing"
    assert out["clean"]["goodput_tok_s"] > 0
    assert out["chaos"]["goodput_tok_s"] > 0
    assert out["clean"]["shed_429"] + out["clean"]["answered"] == 12
    assert out["chaos"]["shed_429"] + out["chaos"]["answered"] == 12
    assert out["goodput_retention"] > 0
    with capsys.disabled():
        print(f"\nfleet chaos bench (tiny/CPU): retention "
              f"{out['goodput_retention']}x, chaos TTFT p99 "
              f"{out['ttft_p99_chaos_s']}s vs clean "
              f"{out['ttft_p99_clean_s']}s, "
              f"{out['restarts_observed']} restart(s), "
              f"{out['chaos']['shed_429']} shed")
