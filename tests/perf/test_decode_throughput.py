"""Decode throughput microbench (VERDICT r2 item 8 done-criterion).

Runs the jitted lax.while_loop generation path and reports tokens/sec.
On the CPU mesh this is a smoke-scale sanity run; on real TPU
(``DSTPU_TEST_ON_TPU=1``) it measures serving decode speed.
"""

import time

import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm


def test_decode_tokens_per_sec(capsys):
    on_tpu = jax.default_backend() != "cpu"
    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    if on_tpu:
        model = causal_lm("gpt2-small", mesh=mesh)
        batch, prompt, new = 8, 128, 128
    else:
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2, hidden_size=128,
                          intermediate_size=256, num_heads=4, vocab_size=512)
        batch, prompt, new = 2, 16, 16
    rng = jax.random.PRNGKey(0)
    toks = jax.random.randint(rng, (batch, prompt), 0, model.config.vocab_size)
    params = model.init(rng, toks)
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "bfloat16" if on_tpu else "float32",
                       "max_out_tokens": prompt + new})
    engine.set_params(params)

    out = engine.generate(toks, max_new_tokens=new)  # warmup + compile
    assert out.shape[1] == prompt + new
    t0 = time.perf_counter()
    out = engine.generate(toks, max_new_tokens=new)
    dt = time.perf_counter() - t0
    tps = batch * new / dt
    with capsys.disabled():
        print(f"\n[perf] decode: {tps:,.0f} tok/s "
              f"(batch={batch}, new={new}, {jax.default_backend()})")
    assert tps > 0
