"""Offload optimizer-step perf decomposition (VERDICT r2 item 4).

The overlapped offload step = D2H grads (bf16, all transfers in flight up
front) + host optimizer compute (csrc kernels, leaf-streamed) + per-leaf
async H2D writeback.  On a directly-attached TPU VM the transfers ride PCIe
and the host step dominates; measured there the criterion is offload-step
<= ~1.5x the device step on the bench-class model.  On THIS runner the
device is reached through a remote relay whose host transfers run at a few
MB/s (measured: 250MB of bf16 grads ~ 50s), so the test asserts the pieces
it can measure meaningfully everywhere:

- host optimizer compute throughput (elements/s/core floor),
- the bf16 grad-transfer path is active (half the bytes of fp32),
- the streamed step never materializes more than one leaf's states.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def test_host_step_throughput_and_bf16_path():
    import ml_dtypes

    from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer

    n = 8_000_000
    params = {"w": np.random.default_rng(0).standard_normal(n).astype(np.float32)}
    opt = OffloadedOptimizer(params, backend="cpu", lr=1e-3)
    g32 = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    gbf = g32.astype(ml_dtypes.bfloat16)
    out = np.empty(n, ml_dtypes.bfloat16)

    opt.begin_step()
    t0 = time.perf_counter()
    opt.step_leaf(0, g32)
    dt32 = time.perf_counter() - t0
    opt.end_step()

    opt.begin_step()
    t0 = time.perf_counter()
    opt.step_leaf_bf16(0, gbf, out)
    dtbf = time.perf_counter() - t0
    opt.end_step()

    eps = max(dt32, dtbf)
    rate = n / eps
    print(f"\n[perf] host adam: fp32 {n/dt32/1e6:.0f}M elem/s, "
          f"bf16g {n/dtbf/1e6:.0f}M elem/s")
    assert rate > 20e6, f"host optimizer step too slow: {rate/1e6:.1f}M elem/s"
    # bf16g writes real updated params
    ref = opt._master[0].astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_engine_offload_transfers_bf16(rng):
    """The device half of the offload step must hand back bf16 grads (half
    the D2H bytes of the old fp32 path) when the engine computes in bf16."""
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel, random_dataset

    x, y = random_dataset(n=16)
    cfg = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 1,
           "bf16": {"enabled": True},
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 1,
                                 "offload_optimizer": {"device": "cpu"}}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, rng=jax.random.PRNGKey(0))
    engine.forward((x[:8], y[:8]))
    from deepspeed_tpu.runtime.dataloader import shard_batch

    batch = shard_batch((x[:8], y[:8]), engine.mesh)
    grads, _, _ = engine._offload_prep_fn(engine.state)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert leaf.dtype == jnp.bfloat16, leaf.dtype
