"""Flight recorder (monitor/flight_recorder.py): ring wraparound ordering,
the dump-on-exception path through the engine (the acceptance criterion:
an injected mid-step exception dumps the preceding collective and step
events in order), signal-handler hygiene (installed only on request), and
thread-stack capture."""

import json
import signal

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.monitor.comms import comm_metrics
from deepspeed_tpu.monitor.flight_recorder import (FlightRecorder,
                                                   get_flight_recorder)
from deepspeed_tpu.monitor.metrics import get_registry


def test_ring_wraparound_keeps_order():
    rec = FlightRecorder(capacity=4).enable()
    for i in range(10):
        rec.record("tick", i=i)
    ev = rec.events()
    assert len(ev) == 4
    assert [e["i"] for e in ev] == [6, 7, 8, 9]
    assert [e["seq"] for e in ev] == [6, 7, 8, 9]   # oldest -> newest
    assert rec._n == 10


def test_disabled_records_nothing():
    rec = FlightRecorder(capacity=4)
    rec.record("tick")
    assert rec.events() == []
    rec.enable()
    rec.record("tick")
    rec.disable()
    rec.record("tock")
    assert [e["kind"] for e in rec.events()] == ["tick"]


def test_dump_contains_events_and_thread_stacks(tmp_path):
    rec = FlightRecorder(capacity=8).enable(dump_dir=str(tmp_path))
    rec.record("step_begin", step=1)
    rec.record("step_end", step=1)
    path = rec.dump(reason="unit test")
    with open(path) as fh:
        payload = json.load(fh)
    assert payload["reason"] == "unit test"
    assert [e["kind"] for e in payload["events"]] == ["step_begin",
                                                      "step_end"]
    # every dump carries all-thread stacks (hang diagnosis); the main
    # thread's stack includes this test function
    assert payload["threads"]
    assert any("test_dump_contains_events" in "\n".join(fr)
               for fr in payload["threads"].values())


def test_signal_handler_installed_only_on_request(tmp_path):
    if not hasattr(signal, "SIGUSR2"):
        pytest.skip("no SIGUSR2 on this platform")
    before = signal.getsignal(signal.SIGUSR2)
    rec = FlightRecorder(capacity=4)
    rec.enable(dump_dir=str(tmp_path))          # enabling does NOT install
    assert not rec.signal_installed
    assert signal.getsignal(signal.SIGUSR2) is before
    try:
        assert rec.install_signal_handler()
        assert rec.signal_installed
        assert signal.getsignal(signal.SIGUSR2) is not before
        rec.record("alive", step=7)
        signal.raise_signal(signal.SIGUSR2)     # delivered synchronously
        dumps = list(tmp_path.glob("ds_flight_*.json"))
        assert dumps, "SIGUSR2 did not produce a dump"
        payload = json.loads(dumps[0].read_text())
        kinds = [e["kind"] for e in payload["events"]]
        assert kinds[-1] == "signal" and "alive" in kinds
    finally:
        rec.uninstall_signal_handler()
    assert signal.getsignal(signal.SIGUSR2) is before


# ---------------------------------------------------------------------------
# engine integration: dump on an injected mid-step exception
# ---------------------------------------------------------------------------


def test_engine_dumps_on_mid_step_exception(tmp_path, mesh8):
    """Acceptance: poisoning the boundary update mid-step produces a dump
    whose event ring still holds the preceding collective and step events,
    in seq order."""
    from deepspeed_tpu.models import causal_lm

    reg = get_registry()
    was = reg.enabled
    reg.reset()
    rec = get_flight_recorder()
    model = causal_lm("llama-tiny", mesh=mesh8, num_layers=1, hidden_size=32,
                      intermediate_size=64, num_heads=2, num_kv_heads=1,
                      vocab_size=128, remat=False)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3,
                                 "stage3_param_persistence_threshold": 0},
           "comms_logger": {"enabled": True},
           "flight_recorder": {"enabled": True, "capacity": 64,
                               "dump_dir": str(tmp_path)},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, mesh=mesh8, rng=jax.random.PRNGKey(5))
    try:
        assert rec.enabled
        assert not rec.signal_installed     # on_signal defaults to False
        tokens = np.asarray(jax.random.randint(
            jax.random.PRNGKey(0), (8, 16), 0, 128), dtype=np.int32)
        engine.forward((tokens, tokens))
        engine.step()                       # one clean step first
        engine.forward((tokens, tokens))    # records the collective commit

        def boom(state):
            raise RuntimeError("injected mid-step fault")

        engine._apply_fn = boom
        with pytest.raises(RuntimeError, match="injected"):
            engine.step()
        dumps = sorted(tmp_path.glob("ds_flight_*.json"))
        assert dumps, "engine did not dump on the injected exception"
        payload = json.loads(dumps[-1].read_text())
        kinds = [e["kind"] for e in payload["events"]]
        # the dump ends with the exception, preceded (in order) by the
        # poisoned step's begin, which follows the micro-batch's collective
        assert kinds[-1] == "exception"
        assert "collective" in kinds and "step_begin" in kinds
        i_coll = max(i for i, k in enumerate(kinds) if k == "collective")
        i_begin = max(i for i, k in enumerate(kinds) if k == "step_begin")
        assert i_coll < i_begin < len(kinds) - 1
        seqs = [e["seq"] for e in payload["events"]]
        assert seqs == sorted(seqs)
        # a second failure does not dump again (once per engine)
        with pytest.raises(RuntimeError):
            engine.step()
        assert len(sorted(tmp_path.glob("ds_flight_*.json"))) == len(dumps)
    finally:
        rec.disable()
        rec.reset()
        comm_metrics.configure(enabled=False)
        comm_metrics.reset()
        reg.reset()
        if not was:
            reg.disable()
