"""Per-request span tracing (monitor/request_trace.py) units, plus the
offline-tool selftests (trace_report / fleet_dump) and the live two-replica
fleet-scrape merge — the ISSUE 7 attribution surface.  Pure host logic:
no jax compiles, runs in milliseconds (tier-1)."""

import json
import os
import sys

import pytest

from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry
from deepspeed_tpu.monitor.request_trace import (PHASES, RequestTracer,
                                                 get_request_tracer,
                                                 get_trace_clock_anchor,
                                                 set_trace_clock_anchor)

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# phase partition / reconciliation
# ---------------------------------------------------------------------------


def test_phase_partition_telescopes_through_preemption():
    """The edge partition must telescope to exactly t_finish - t_submit,
    including across a preempt -> requeue -> re-admit -> re-prefill cycle
    (the paged-KV resume path): every instant of the request's lifetime
    belongs to exactly one of the four phases."""
    tr = RequestTracer().enable()
    tr.submit(1, 10.0, prompt_len=8, max_new=16)
    tr.admit(1, 0, 11.0)          # queue          = 1.0
    tr.decode_start(1, 13.0)      # prefill       += 2.0
    tr.preempt(1, 14.0)           # decode        += 1.0
    tr.admit(1, 1, 16.0)          # preempted_wait = 2.0
    tr.decode_start(1, 17.0)      # prefill       += 1.0 (re-prefill)
    tr.finish(1, 19.0, "eos", 5)  # decode        += 2.0
    (rec,) = tr.completed()
    assert rec["phases"] == {"queue": 1.0, "prefill": 3.0, "decode": 3.0,
                             "preempted_wait": 2.0}
    assert sum(rec["phases"].values()) == rec["latency_s"] == 9.0
    assert rec["preemptions"] == 1 and rec["reason"] == "eos"
    assert rec["t_first_token"] == 13.0     # not re-stamped on resume
    assert rec["edges"][-1] == (19.0, "finish")
    assert tr.open_count == 0


def test_phase_histograms_record_once_per_finish():
    """Each finish records exactly one observation into every
    ``ds_serve_phase_*_seconds`` histogram, and the four values sum to
    the request's latency — the aggregate mirror of the per-request
    telescoping (asserted via count/sum deltas on the global registry,
    which the tracer's histograms live on)."""
    reg = get_registry()
    was = reg.enabled
    reg.enable()
    try:
        before = {p: (reg.get(f"ds_serve_phase_{p}_seconds").count,
                      reg.get(f"ds_serve_phase_{p}_seconds").sum)
                  for p in PHASES}
        tr = RequestTracer().enable()
        tr.submit(2, 0.0, 4, 8)
        tr.admit(2, 0, 0.5)
        tr.decode_start(2, 1.25)
        tr.finish(2, 3.0, "length", 8)
        deltas = {}
        for p in PHASES:
            h = reg.get(f"ds_serve_phase_{p}_seconds")
            c0, s0 = before[p]
            assert h.count - c0 == 1, p
            deltas[p] = h.sum - s0
        assert deltas["queue"] == pytest.approx(0.5)
        assert deltas["prefill"] == pytest.approx(0.75)
        assert deltas["decode"] == pytest.approx(1.75)
        assert deltas["preempted_wait"] == 0.0
        assert sum(deltas.values()) == pytest.approx(3.0)
    finally:
        reg._enabled = was


# ---------------------------------------------------------------------------
# retention: ring + slowest heap
# ---------------------------------------------------------------------------


def _complete(tr, rid, t0, latency):
    tr.submit(rid, t0, 4, 4)
    tr.admit(rid, 0, t0 + latency * 0.25)
    tr.decode_start(rid, t0 + latency * 0.5)
    tr.finish(rid, t0 + latency, "eos", 4)


def test_ring_churn_keeps_slowest_exemplars():
    """A slow request must survive ring churn via the slowest-exemplar
    heap: the tail stays inspectable however long the run."""
    tr = RequestTracer(ring=4, slowest_k=2).enable()
    _complete(tr, 0, 0.0, 50.0)              # the slowest, finished first
    for rid in range(1, 10):
        _complete(tr, rid, 100.0 + rid, 1.0 + rid * 0.01)
    recent_ids = {r["id"] for r in tr._ring}
    assert 0 not in recent_ids               # churned out of the ring...
    all_ids = {r["id"] for r in tr.completed()}
    assert 0 in all_ids                      # ...but retained by the heap
    assert tr.slowest(1)[0]["id"] == 0
    assert tr.completed_total == 10
    # slowest list is sorted most-severe first
    lats = [r["latency_s"] for r in tr.slowest()]
    assert lats == sorted(lats, reverse=True)
    # completed() dedups ring∩heap and orders by completion time
    fins = [r["t_finish"] for r in tr.completed()]
    assert fins == sorted(fins)
    # max_spans cap: overflow counts instead of growing the timeline
    tr2 = RequestTracer(max_spans=2).enable()
    tr2.submit(7, 0.0, 4, 4)
    for i in range(5):
        tr2.span(7, "decode_block", float(i), i + 0.5, 3)
    tr2.finish(7, 9.0, "eos", 4)
    (rec,) = tr2.completed()
    assert len(rec["spans"]) == 2 and rec["spans_dropped"] == 3


def test_tail_attribution_finds_dominant_phase():
    """Tail attribution answers "why is the p99 slow": among requests
    above the p-quantile cut, which phase holds the time."""
    tr = RequestTracer(ring=256).enable()
    for rid in range(99):                    # fast, decode-dominated
        _complete(tr, rid, float(rid), 0.1)
    # one pathological straggler: 60s in queue, fast after admission
    tr.submit(99, 1000.0, 4, 4)
    tr.admit(99, 0, 1060.0)
    tr.decode_start(99, 1060.5)
    tr.finish(99, 1061.0, "eos", 4)
    ta = tr.tail_attribution(p=0.99)
    assert ta["n"] == 100 and ta["tail_n"] == 1
    assert ta["dominant_phase"] == "queue"
    assert ta["phase_share"]["queue"] > 0.9
    assert sum(ta["phase_share"].values()) == pytest.approx(1.0)
    assert ta["exemplars"] == [99]
    # empty tracer degrades cleanly
    assert RequestTracer().tail_attribution()["dominant_phase"] is None


# ---------------------------------------------------------------------------
# disabled-path contract + lifecycle guards
# ---------------------------------------------------------------------------


def test_disabled_hooks_allocate_nothing():
    """The metrics.py hot-path contract: a DISABLED tracer's lifecycle
    hooks are one attribute-load + branch and allocate nothing per
    request (the serving loop calls them unconditionally)."""
    tr = RequestTracer()
    assert not tr.enabled
    for hook in range(2):                    # warm any lazy interpreter state
        tr.submit(1, 0.0, 4, 4)
        tr.admit(1, 0, 0.1)
        tr.span(1, "prefill_chunk", 0.1, 0.2, 4)
        tr.decode_start(1, 0.2)
        tr.span(1, "decode_block", 0.2, 0.3, 3)
        tr.preempt(1, 0.3)
        tr.finish(1, 0.4, "eos", 3)
    before = sys.getallocatedblocks()
    for _ in range(1000):
        tr.submit(1, 0.0, 4, 4)
        tr.admit(1, 0, 0.1)
        tr.span(1, "prefill_chunk", 0.1, 0.2, 4)
        tr.decode_start(1, 0.2)
        tr.span(1, "decode_block", 0.2, 0.3, 3)
        tr.preempt(1, 0.3)
        tr.finish(1, 0.4, "eos", 3)
    delta = sys.getallocatedblocks() - before
    assert tr.open_count == 0 and not tr.completed()
    # interpreter internals may wiggle a few blocks, never per-call
    assert delta < 100, delta


def test_disable_drops_in_flight_timelines():
    """disable() while requests are mid-flight (bench teardown, operator
    toggle) must clear the open timelines: their finish edges will never
    arrive while disabled, so keeping them would leak phantom 'open'
    requests forever and trip the span-completeness guard on a later
    re-enable.  Retained completions survive the toggle."""
    tr = RequestTracer().enable()
    _complete(tr, 1, 0.0, 1.0)
    tr.submit(2, 5.0, 4, 4)
    tr.admit(2, 0, 5.5)
    assert tr.open_count == 1
    tr.disable()
    assert tr.open_count == 0
    tr.finish(2, 9.0, "eos", 4)              # no-op, no resurrection
    tr.enable()
    tr.finish(2, 9.0, "eos", 4)              # unknown rid now: no-op
    assert tr.open_count == 0 and tr.completed_total == 1
    assert [r["id"] for r in tr.completed()] == [1]


def test_unknown_or_preenable_requests_are_ignored():
    """Edges for requests the tracer never saw (submitted while tracing
    was off, or plain bogus ids) must be silent no-ops — enabling the
    tracer mid-run cannot corrupt or grow state."""
    tr = RequestTracer().enable()
    tr.admit(404, 0, 1.0)
    tr.decode_start(404, 2.0)
    tr.span(404, "decode_block", 2.0, 2.5, 3)
    tr.preempt(404, 3.0)
    tr.finish(404, 4.0, "eos", 3)
    assert tr.open_count == 0 and not tr.completed()
    assert tr.completed_total == 0


def test_configure_and_reset():
    tr = RequestTracer(ring=8, slowest_k=4).enable()
    for rid in range(6):
        _complete(tr, rid, float(rid), 1.0 + rid)
    tr.configure(slowest_k=2)                # keeps the 2 slowest
    assert [r["id"] for r in tr.slowest()] == [5, 4]
    tr.configure(ring=2)
    assert len(tr._ring) == 2
    tr.reset()
    assert not tr.completed() and tr.completed_total == 0
    # the process-global accessor hands back one shared instance
    assert get_request_tracer() is get_request_tracer()


# ---------------------------------------------------------------------------
# exports: snapshot + perfetto clock mapping
# ---------------------------------------------------------------------------


def test_perfetto_export_maps_onto_trace_clock():
    """`/requestz?format=perfetto` timestamps must be microseconds since
    the trace-session anchor — the same epoch jax's perfetto file uses —
    so both files load in one Perfetto session on a shared clock."""
    anchor = set_trace_clock_anchor()
    a = anchor["perf"]
    tr = RequestTracer().enable()
    tr.submit(3, a + 0.25, 4, 8)
    tr.admit(3, 0, a + 0.5)
    tr.span(3, "prefill_chunk", a + 0.5, a + 0.6, 4)
    tr.decode_start(3, a + 0.75)
    tr.finish(3, a + 1.0, "eos", 8)
    trace = tr.perfetto_trace()
    assert trace["otherData"]["clock_source"] == "trace_session"
    assert trace["otherData"]["clock_anchor_unix"] == anchor["unix"]
    xs = {(e["tid"], e["name"]): e for e in trace["traceEvents"]
          if e.get("ph") == "X"}
    phases_tid = 2 * 3
    q = xs[(phases_tid, "queue")]
    assert q["ts"] == pytest.approx(0.25e6) and \
        q["dur"] == pytest.approx(0.25e6)
    d = xs[(phases_tid, "decode")]
    assert d["ts"] == pytest.approx(0.75e6) and \
        d["dur"] == pytest.approx(0.25e6)
    sp = xs[(phases_tid + 1, "prefill_chunk")]
    assert sp["ts"] == pytest.approx(0.5e6) and \
        sp["args"]["tokens"] == 4
    # thread metadata names the request for the Perfetto track list
    metas = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
    names = {e["args"]["name"] for e in metas}
    assert {"ds_requests", "req 3 phases", "req 3 spans"} <= names
    # the whole export is valid JSON (what the endpoint serves)
    json.loads(json.dumps(trace))
    # the module-level accessor mirrors the last stamp
    assert get_trace_clock_anchor()["perf"] == anchor["perf"]


def test_snapshot_shape():
    tr = RequestTracer().enable()
    _complete(tr, 11, 0.0, 2.0)
    snap = tr.snapshot(limit=4)
    assert snap["enabled"] and snap["completed_total"] == 1
    assert snap["open"] == 0 and snap["retained"] == 1
    assert snap["tail_attribution"]["n"] == 1
    assert snap["recent"][0]["id"] == 11
    assert snap["slowest"][0]["edges"][-1] == [2.0, "finish"]
    assert "clock" in snap
    json.loads(json.dumps(snap))             # endpoint-serializable


# ---------------------------------------------------------------------------
# offline tools: selftests wired as tier-1 (they cannot silently rot)
# ---------------------------------------------------------------------------


def test_trace_report_selftest():
    """tools/trace_report.py --selftest parses its bundled synthetic
    perfetto fixture and asserts the phase partition."""
    trace_report = _tool("trace_report")
    assert trace_report.main(["trace_report", "--selftest"]) == 0


def test_fleet_dump_selftest():
    """tools/fleet_dump.py --selftest merges two synthetic replicas built
    through the REAL registry and asserts counter sums / gauge spreads /
    merged-histogram quantiles."""
    fleet_dump = _tool("fleet_dump")
    assert fleet_dump.main(["fleet_dump", "--selftest"]) == 0


def test_metrics_dump_requests_table(tmp_path, capsys):
    """tools/metrics_dump.py --requests renders the slowest-exemplar
    table (id, latency, phase breakdown, preemptions, reason) plus the
    tail-attribution line from a saved /requestz snapshot."""
    metrics_dump = _tool("metrics_dump")
    tr = RequestTracer().enable()
    _complete(tr, 5, 0.0, 4.0)
    tr.submit(6, 10.0, 4, 4)
    tr.admit(6, 0, 11.0)
    tr.decode_start(6, 11.5)
    tr.preempt(6, 12.0)
    tr.admit(6, 1, 13.0)
    tr.decode_start(6, 13.5)
    tr.finish(6, 30.0, "length", 4)
    snap = tmp_path / "requestz.json"
    snap.write_text(json.dumps(tr.snapshot()))
    assert metrics_dump.main(
        ["metrics_dump", "--requests", str(snap)]) == 0
    out = capsys.readouterr().out
    assert "slowest 2 of 2 completed" in out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    header = lines[1].split()
    assert header[:6] == ["id", "latency_s", "queue_s", "prefill_s",
                          "decode_s", "preempt_wait_s"]
    row6 = next(ln for ln in lines if ln.startswith("6 "))
    assert "length" in row6 and " 1 " in row6   # reason + preemption count
    assert "dominant=" in out                   # tail-attribution line
    # empty snapshot: a helpful hint, not a crash
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(RequestTracer().snapshot()))
    assert metrics_dump.main(
        ["metrics_dump", "--requests", str(empty)]) == 0
    assert "is the tracer enabled" in capsys.readouterr().out


def test_fleet_dump_merges_two_live_endpoints():
    """The acceptance run: two LIVE /statz endpoints (each its own
    registry + HTTP server, the bench-child / router-replica shape)
    scraped and merged over real HTTP — counters sum, gauges spread,
    histograms merge by bucket counts, kinds ride the ?kinds=1 query."""
    from deepspeed_tpu.monitor.server import MetricsServer

    fleet_dump = _tool("fleet_dump")
    servers, urls = [], []
    try:
        for depth, lat in ((2, 0.01), (8, 1.9)):
            reg = MetricsRegistry().enable()
            reg.counter("ds_serve_submitted_total").inc(depth * 10)
            reg.gauge("ds_serve_queue_depth").set(depth)
            for _ in range(50):
                reg.histogram(
                    "ds_serve_request_latency_seconds").record(lat)
            srv = MetricsServer(reg, port=0).start()
            servers.append(srv)
            urls.append(f"127.0.0.1:{srv.port}")
        snaps, kinds = {}, {}
        for i, u in enumerate(urls):
            data = fleet_dump.fetch_statz(u)
            snaps[f"r{i}"] = data["metrics"]
            kinds.update(data["kinds"])
        # the ?kinds=1 contract: merge decisions come from real kinds,
        # not naming heuristics
        assert kinds["ds_serve_queue_depth"] == "gauge"
        assert kinds["ds_serve_submitted_total"] == "counter"
        fleet = fleet_dump.merge_snapshots(snaps, kinds)
        sub = fleet["ds_serve_submitted_total"]
        assert sub["sum"] == 100 and sub["per_replica"]["r1"] == 80
        q = fleet["ds_serve_queue_depth"]
        assert (q["min"], q["max"]) == (2, 8) and q["skew"] > 1
        lat = fleet["ds_serve_request_latency_seconds"]
        assert lat["count"] == 100
        # fleet p99 comes from the MERGED distribution: it must land in
        # the slow replica's bucket, which averaging per-replica p99s
        # could never say
        assert 1.0 < lat["p99"] <= 3.2
        table = fleet_dump.render(fleet, sorted(snaps))
        assert "ds_serve_queue_depth" in table and "r1" in table
    finally:
        for srv in servers:
            srv.stop()


# ---------------------------------------------------------------------------
# training step timeline (the serve tracer's twin, same exporter)
# ---------------------------------------------------------------------------

def test_step_timeline_records_steps_micros_comm_and_events():
    """micro/boundary/event recording, the analytic comm-plan overlay
    split byte-proportionally across the step window, bubble_share on
    the step slice, and the shared _perfetto_doc envelope."""
    from deepspeed_tpu.monitor.request_trace import StepTimeline

    tl = StepTimeline()
    # disabled default: hooks are no-ops (hot-path contract)
    tl.micro(1, 1, 1.0)
    tl.boundary(1, 2.0)
    assert tl.steps() == [] and tl.steps_total == 0

    tl.enable()
    tl.boundary(0, 0.005)                      # seeds the open time
    tl.micro(1, 1, 0.010)
    tl.micro(1, 2, 0.020)
    tl.event("anomaly_skip", 0.025, anomaly="nonfinite_grad", step=1)
    plan = {"micro": [("all_reduce", 2, 3 * (1 << 20), "bf16", 8)],
            "boundary": [("all_gather", 1, 1 << 20, "bf16", 8)]}
    tl.boundary(1, 0.030, comm_plan=plan, bubble_share=0.25)
    assert tl.steps_total == 2

    snap = tl.snapshot()
    rec = snap["steps"][-1]
    assert rec["step"] == 1 and rec["bubble_share"] == 0.25
    assert [m[0] for m in rec["micros"]] == [1, 2]
    assert len(rec["comm_plan"]) == 2
    assert rec["events"][0][0] == "anomaly_skip"

    anchor = {"perf": 0.0, "unix": 1000.0, "source": "test"}
    doc = tl.perfetto_trace(anchor=anchor)
    assert doc["otherData"]["clock_anchor_unix"] == 1000.0
    ev = doc["traceEvents"]
    step = [e for e in ev if e.get("name") == "step 1"][0]
    assert step["ts"] == 5000.0 and step["dur"] == 25000.0
    assert step["args"]["bubble_share"] == 0.25
    micros = [e for e in ev if e.get("name", "").startswith("micro ")]
    assert [m["name"] for m in micros] == ["micro 1", "micro 2"]
    assert micros[0]["ts"] == 5000.0 and micros[0]["dur"] == 5000.0
    # byte-weighted overlay: 3MiB/4MiB of the 25ms window, then 1MiB
    comm = [e for e in ev if e["args"].get("analytic")]
    assert [c["name"] for c in comm] == ["all_reduce", "all_gather"]
    assert comm[0]["dur"] == pytest.approx(18750.0)
    assert comm[1]["ts"] == pytest.approx(5000.0 + 18750.0)
    inst = [e for e in ev if e.get("ph") == "i"][0]
    assert inst["name"] == "anomaly_skip" and inst["ts"] == 25000.0

    tl.disable()
    tl.micro(9, 1, 9.0)
    assert tl.snapshot()["steps_total"] == 2


def test_requestz_kind_train_serves_the_step_timeline():
    """/requestz?kind=train exposes the process-global StepTimeline
    through the SAME endpoint + format contract as the request tracer
    (snapshot JSON and ?format=perfetto)."""
    import time
    import urllib.request

    from deepspeed_tpu.monitor.request_trace import get_step_timeline
    from deepspeed_tpu.monitor.server import MetricsServer

    tl = get_step_timeline()
    tl.reset()
    tl.enable()
    srv = None
    try:
        base = __import__("time").perf_counter()
        tl.boundary(0, base)
        tl.micro(1, 1, base + 0.01)
        tl.boundary(1, base + 0.02, bubble_share=0.5)
        srv = MetricsServer(MetricsRegistry().enable(), port=0).start()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/requestz?kind=train",
                timeout=5) as resp:
            snap = json.load(resp)
        assert snap["steps_total"] == 2
        assert snap["steps"][-1]["bubble_share"] == 0.5
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/requestz?kind=train"
                "&format=perfetto", timeout=5) as resp:
            doc = json.load(resp)
        names = {e.get("name") for e in doc["traceEvents"]}
        assert "step 1" in names
        procs = [e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert procs == ["ds_train_steps"]
    finally:
        if srv is not None:
            srv.stop()
        tl.disable()
        tl.reset()
