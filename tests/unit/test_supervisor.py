"""tools/train_supervisor.py: bounded-retry restart loop + the end-to-end
preemption acceptance — SIGTERM mid-train → emergency save at the
boundary → supervisor restart → resume from the newest valid checkpoint
reaches the SAME loss as an uninterrupted run (rtol 2e-5, the PR 6 parity
bar)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")
_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_train_supervisor_selftest():
    """The retry/backoff/preempt state machine against synthetic
    children (crash-twice-then-succeed, budget exhaustion, preempt exit
    without backoff, backoff cap, DS_SUPERVISOR_RESTART visibility)."""
    sup = _tool("train_supervisor")
    assert sup.main(["train_supervisor", "--selftest"]) == 0


def test_supervisor_sigterm_forwarding_no_restart():
    """SIGTERM to the supervisor is forwarded to the child (its grace
    window runs) and the job is NOT restarted — whole-job preemption."""
    sup_mod = _tool("train_supervisor")
    prog = ("import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(5))\n"
            "time.sleep(30)\n")
    sup = sup_mod.TrainSupervisor([sys.executable, "-c", prog],
                                  max_restarts=5, backoff_base=0.0,
                                  grace_s=20.0)
    t = threading.Thread(
        target=lambda: (time.sleep(0.8),
                        os.kill(os.getpid(), signal.SIGTERM)), daemon=True)
    t.start()
    t0 = time.time()
    rc = sup.run()
    assert rc == 5
    assert sup.restarts == 0
    assert time.time() - t0 < 15, "grace forwarding should be fast"


# ---------------------------------------------------------------------------
# the acceptance e2e: kill mid-train, resume to loss parity
# ---------------------------------------------------------------------------

_TRAIN_SCRIPT = r'''
import os, sys

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8 "
    "--xla_cpu_enable_concurrency_optimized_scheduler=false")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["DS_ACCELERATOR"] = "cpu"
sys.path.insert(0, {repo!r})

import json
import signal

import numpy as np
import jax
import jax.numpy as jnp

try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DSTPU_XLA_CACHE_DIR",
                                     "/tmp/dstpu_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

import deepspeed_tpu

SAVE_DIR, RESULT = sys.argv[1], sys.argv[2]
TOTAL_STEPS, KILL_AT = 8, 4


def batch_for(step):
    # data position IS the step index: resume correctness is observable
    # as loss parity only if the resumed run sees the same batches
    rng = np.random.default_rng(1234 + step)
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.normal(size=(8, 4)).astype(np.float32)
    return x, y


def loss_fn(params, batch, rng):
    x, y = batch
    out = jnp.tanh(x @ params["w1"]) @ params["w2"]
    return jnp.mean((out - y) ** 2)


init = np.random.default_rng(0)
params = {{"w1": jnp.asarray(init.normal(size=(8, 16)) * 0.3, jnp.float32),
           "w2": jnp.asarray(init.normal(size=(16, 4)) * 0.3, jnp.float32)}}
cfg = {{"train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
        "steps_per_print": 10**9}}
engine, _, _, _ = deepspeed_tpu.initialize(
    config=cfg, loss_fn=loss_fn, model_parameters=params)

start = 0
ckpt_dir, client_state = engine.load_checkpoint(SAVE_DIR)
if ckpt_dir is not None:
    start = int(client_state["data_step"])
    print(f"resumed from {{ckpt_dir}} at data_step={{start}}", flush=True)

holder = {{"next": start}}
engine.enable_preemption_save(
    SAVE_DIR, client_state_fn=lambda: {{"data_step": holder["next"]}},
    exit_after=True)

incarnation = int(os.environ.get("DS_SUPERVISOR_RESTART", "0"))
kill = os.environ.get("DS_TEST_KILL") == "1" and incarnation == 0

last = None
for i in range(start, TOTAL_STEPS):
    holder["next"] = i + 1            # the boundary save resumes AFTER i
    if kill and i == KILL_AT:
        # the preemption signal arrives mid-step; the optimizer boundary
        # of THIS step takes the emergency save and exits 243
        os.kill(os.getpid(), signal.SIGTERM)
    loss = engine.forward(batch_for(i))
    engine.step()
    last = float(loss)

with open(RESULT, "w") as fh:
    json.dump({{"final_loss": last, "ran_from": start}}, fh)
'''


def test_sigterm_midtrain_supervisor_resume_matches_uninterrupted(tmp_path):
    """SIGTERM lands mid-train on incarnation 0 → the engine's boundary
    hook takes an emergency save (dataloader position in client_state)
    and exits with the preempted code → the supervisor restarts
    immediately → incarnation 1 resumes from the newest valid checkpoint
    at the exact data step → the final loss matches an uninterrupted run
    at rtol 2e-5."""
    sup_mod = _tool("train_supervisor")
    script = tmp_path / "train.py"
    script.write_text(_TRAIN_SCRIPT.format(repo=_REPO))

    # run 1: supervised, killed at step 4 of 8 on incarnation 0
    kill_dir = tmp_path / "kill_ckpts"
    kill_result = tmp_path / "kill_result.json"
    env = dict(os.environ)
    env["DS_TEST_KILL"] = "1"
    sup = sup_mod.TrainSupervisor(
        [sys.executable, str(script), str(kill_dir), str(kill_result)],
        max_restarts=2, backoff_base=0.01, env=env)
    rc = sup.run()
    assert rc == 0, "supervised run did not complete"
    assert sup.preempt_restarts == 1 and sup.crash_restarts == 0
    killed = json.loads(kill_result.read_text())
    assert killed["ran_from"] == 5, \
        "resume was not step-accurate (client_state data_step)"
    # the emergency checkpoint is a valid tag under the manifest contract
    from deepspeed_tpu.runtime.checkpoint_engine import atomic

    tag = atomic.read_latest(str(kill_dir))
    assert tag is not None
    assert atomic.verify_dir(os.path.join(str(kill_dir), tag),
                             level="full").ok

    # run 2: uninterrupted, same data schedule
    ref_result = tmp_path / "ref_result.json"
    env2 = dict(os.environ)
    env2.pop("DS_TEST_KILL", None)
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "ref_ckpts"),
         str(ref_result)], env=env2, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    ref = json.loads(ref_result.read_text())
    assert ref["ran_from"] == 0

    assert killed["final_loss"] == pytest.approx(ref["final_loss"],
                                                 rel=2e-5)
