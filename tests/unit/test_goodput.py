"""Run-level goodput ledger (ISSUE 18): telescoping wall-clock
attribution, restart-aware stitching, SLO burn-rate alerts.

Three layers: (1) ``goodput_core`` units — the attribution state machine
(stack + cursor + idle residual) and the stitcher's gap arithmetic;
(2) ``GoodputLedger`` process wiring — gauges, jsonl persistence, the
SLO watcher, ``/goodputz``; (3) engine e2e — a real train engine's
seams feed the ledger, checkpoint flight events reconcile with ledger
event rows by id, and THE chaos acceptance: kill → restart → resume →
anomaly rollback stitches into one telescoping run with nonzero
``restart_downtime`` and ``rollback``.
"""

import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.monitor import goodput_core as core
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.goodput import (GoodputLedger, SloWatcher,
                                           get_goodput_ledger)
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.testing import chaos
from tests.unit.simple_model import SimpleModel, random_dataset

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")

X, Y = random_dataset(n=32)


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# LedgerCore units (jax-free attribution arithmetic)
# ---------------------------------------------------------------------------


def test_core_telescopes_with_nested_regions():
    """Synthetic clock: nested regions attribute to the INNERMOST open
    region, pop returns direct seconds (nested time excluded), idle is
    the residual, and the snapshot telescopes exactly."""
    c = core.LedgerCore(start=100.0)
    c.push("compute", 101.0)            # [100, 101) idle
    c.push("checkpoint_save", 103.0)    # [101, 103) compute
    cat, direct = c.pop(104.5)          # [103, 104.5) checkpoint_save
    assert cat == "checkpoint_save" and direct == pytest.approx(1.5)
    cat, direct = c.pop(106.0)          # [104.5, 106) compute again
    assert cat == "compute"
    assert direct == pytest.approx(3.5)  # 2.0 + 1.5, MINUS the nested 1.5
    snap = c.snapshot(110.0)            # [106, 110) idle
    assert snap["wall_s"] == pytest.approx(10.0)
    assert snap["categories"]["compute"] == pytest.approx(3.5)
    assert snap["categories"]["checkpoint_save"] == pytest.approx(1.5)
    assert snap["categories"]["idle"] == pytest.approx(5.0)
    assert core.telescopes(snap)
    assert snap["goodput_ratio"] == pytest.approx(0.35)
    # snapshot with a region still OPEN telescopes too (open accrual
    # counts toward its category, not idle)
    c.push("recompile", 110.0)
    snap = c.snapshot(112.0)
    assert snap["categories"]["recompile"] == pytest.approx(2.0)
    assert snap["open_regions"] == ["recompile"]
    assert core.telescopes(snap)


def test_core_shift_clamps_and_preserves_sum():
    c = core.LedgerCore(start=0.0)
    c.push("compute", 0.0)
    c.pop(4.0)
    assert c.shift("compute", "exposed_comm", 1.5) == pytest.approx(1.5)
    # clamped at what src holds: asking for 10 moves only the 2.5 left
    assert c.shift("compute", "anomaly_skip", 10.0) == pytest.approx(2.5)
    snap = c.snapshot(4.0)
    assert snap["categories"]["compute"] == 0.0
    assert snap["categories"]["exposed_comm"] == pytest.approx(1.5)
    assert snap["categories"]["anomaly_skip"] == pytest.approx(2.5)
    assert core.telescopes(snap)
    with pytest.raises(ValueError):
        c.shift("compute", "nonsense", 1.0)


def test_core_crash_tolerance_edges():
    """Pop with nothing open is a no-op; a retreating clock attributes
    nothing (never negative); unknown categories are a closed-set error."""
    c = core.LedgerCore(start=0.0)
    assert c.pop(1.0) == (None, 0.0)
    c.push("compute", 2.0)
    c.pop(1.5)                           # clock retreat: 0 attributed
    assert c.totals["compute"] == 0.0
    with pytest.raises(ValueError):
        c.push("espresso_break", 3.0)
    assert core.telescopes(c.snapshot(5.0))


def test_stitch_filters_run_id_for_fleet_jsonl(tmp_path):
    """A serve fleet shares ONE jsonl with per-replica run ids
    (``<run>-r<i>``): stitch(run_id=) folds each replica independently
    and ignores the others' rows."""
    path = str(tmp_path / "fleet.jsonl")
    for rid, up, comp in (("s-r0", 10.0, 9.0), ("s-r1", 8.0, 4.0)):
        snap = {"categories": {"compute": comp, "idle": up - comp},
                "goodput_ratio": comp / up, "tokens": 100, "steps": 5}
        core.append_row(path, core.start_row(rid, 0, "serve", 1000.0))
        core.append_row(path, core.tick_row(rid, 0, 1000.0 + up, up, snap))
    r0 = core.stitch(core.read_rows(path), run_id="s-r0")
    r1 = core.stitch(core.read_rows(path), run_id="s-r1")
    assert r0["wall_s"] == pytest.approx(10.0)
    assert r1["wall_s"] == pytest.approx(8.0)
    assert r0["goodput_ratio"] == pytest.approx(0.9)
    assert r1["goodput_ratio"] == pytest.approx(0.5)
    assert core.telescopes(r0) and core.telescopes(r1)


# ---------------------------------------------------------------------------
# GoodputLedger wiring: gauges, jsonl, SLO watcher, /goodputz
# ---------------------------------------------------------------------------


def test_ledger_disabled_is_free_and_inert():
    gp = GoodputLedger()
    gp.push("compute")
    assert gp.pop() == 0.0
    assert gp.shift("compute", "exposed_comm", 1.0) == 0.0
    gp.add_tokens(100)
    assert gp.snapshot() == {"enabled": False}
    assert gp.note_event("checkpoint_save", 1.0) == ""
    assert gp.tick(force=True) is None


def test_ledger_gauges_jsonl_and_slo_burn(tmp_path):
    """One enabled ledger: a compute region + tokens, then a forced tick
    exports ``ds_run_goodput_ratio`` + ``ds_run_time_seconds{category=}``,
    persists start/tick rows, and the ``goodput_ratio`` MIN rule (set
    impossibly high) burns — counter + flight event + jsonl row."""
    reg = get_registry()
    reg.enable()
    flight = get_flight_recorder()
    flight.enable(capacity=64)
    path = str(tmp_path / "runledger.jsonl")
    gp = GoodputLedger()
    gp.enable(path=path, run_id="t1", role="train", incarnation=0,
              slo_rules={"goodput_ratio": 0.9999})
    try:
        gp.push("compute")
        time.sleep(0.02)
        gp.pop()
        gp.add_tokens(512)
        gp.set_steps(2)
        snap = gp.tick(force=True)
        assert snap is not None and core.telescopes(snap)
        assert snap["categories"]["compute"] > 0.0
        assert reg.get("ds_run_goodput_ratio").value == pytest.approx(
            snap["goodput_ratio"])
        assert reg.get("ds_run_time_seconds",
                       {"category": "compute"}).value > 0.0
        # the MIN rule burned (a mostly-idle run cannot hit 0.9999)
        assert reg.get("ds_slo_burn_total",
                       {"rule": "goodput_ratio"}).value >= 1
        assert any(e["kind"] == "slo_burn" and e["rule"] == "goodput_ratio"
                   for e in flight.events())
        rows = core.read_rows(path)
        kinds = [r["kind"] for r in rows]
        assert kinds[0] == "start" and "tick" in kinds
        assert "slo_burn" in kinds
        rep = core.stitch(rows)
        assert rep["run_id"] == "t1" and core.telescopes(rep)
        assert rep["slo_burns"]["goodput_ratio"] >= 1
        assert rep["tokens"] == 512 and rep["steps"] == 2
    finally:
        gp.disable()
        flight.disable()
        reg.disable()


def test_slo_watcher_serving_rules():
    """ttft_p99_s (MAX, off the serving TTFT histogram) and shed_ratio
    (MAX, shed/submitted counters) burn only when breached; absent
    series are skipped, not burned."""
    reg = get_registry()
    reg.enable()
    try:
        w = SloWatcher({"ttft_p99_s": 0.1, "shed_ratio": 0.25,
                        "unknown_rule": 1.0})
        assert set(w.rules) == {"ttft_p99_s", "shed_ratio"}
        gp = GoodputLedger()
        gp.enable(run_id="slo-t", role="serve", incarnation=0)
        try:
            # no serving series yet: nothing to observe, no burns
            assert w.evaluate({"goodput_ratio": 1.0}, gp) == 0
            hist = reg.histogram("ds_serve_ttft_seconds")
            for _ in range(20):
                hist.record(0.5)             # p99 far above the 0.1 target
            shed = reg.counter("ds_serve_shed_total")
            sub = reg.counter("ds_serve_submitted_total")
            sub.inc(10)
            shed.inc(1)                      # 0.1 <= 0.25: healthy
            assert w.evaluate({"goodput_ratio": 1.0}, gp) == 1   # ttft only
            shed.inc(9)                      # 10/19 > 0.25: both burn
            assert w.evaluate({"goodput_ratio": 1.0}, gp) == 2
            assert reg.get("ds_slo_burn_total",
                           {"rule": "ttft_p99_s"}).value == 2
            assert reg.get("ds_slo_burn_total",
                           {"rule": "shed_ratio"}).value == 1
        finally:
            gp.disable()
    finally:
        reg.disable()


def test_goodputz_endpoint():
    """GET /goodputz serves the live process-global ledger snapshot."""
    from deepspeed_tpu.monitor.metrics import MetricsRegistry
    from deepspeed_tpu.monitor.server import MetricsServer

    reg = MetricsRegistry().enable()
    gp = get_goodput_ledger()
    gp.enable(run_id="zz-run", role="train", incarnation=0)
    server = MetricsServer(reg, port=0).start()
    try:
        gp.push("compute")
        time.sleep(0.01)
        gp.pop()
        with urllib.request.urlopen(f"{server.url}/goodputz",
                                    timeout=5) as r:
            snap = json.load(r)
        assert snap["enabled"] is True and snap["run_id"] == "zz-run"
        assert snap["categories"]["compute"] > 0.0
        assert core.telescopes(snap)
        # the endpoint is listed on the index page
        with urllib.request.urlopen(server.url + "/", timeout=5) as r:
            assert b"/goodputz" in r.read()
    finally:
        server.stop()
        gp.disable()


def test_goodput_report_tool_selftest():
    """tools/goodput_report.py --selftest: synth ledger -> stitch ->
    telescoping + render/diff + CLI + torn-line tolerance (and DSL003
    keeps its import closure jax-free)."""
    rep = _tool("goodput_report")
    assert rep.selftest() == 0


def test_bench_goodput_window_reconciles():
    """bench.goodput_window: the snapshot-delta block telescopes and the
    token count reconciles exactly against steps * batch * seq."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    try:
        import bench
    finally:
        sys.path.pop(0)
    zero = {c: 0.0 for c in core.CATEGORIES}
    before = {"wall_s": 2.0, "tokens": 100,
              "categories": dict(zero, compute=1.5, idle=0.5)}
    after = {"wall_s": 5.0, "tokens": 1636,
             "categories": dict(zero, compute=4.2, recompile=0.3,
                                idle=0.5)}
    blk = bench.goodput_window(before, after, loop_s=2.9,
                               tokens_expected=1536)
    assert blk["wall_s"] == pytest.approx(3.0)
    assert blk["telescopes"] is True
    assert blk["goodput_ratio"] == pytest.approx(2.7 / 3.0, abs=1e-4)
    assert blk["tokens"] == 1536 and blk["tokens_reconcile"] is True
    assert blk["categories"]["recompile"] == pytest.approx(0.3)
    assert "idle" not in blk["categories"]     # zero-delta categories drop


# ---------------------------------------------------------------------------
# engine e2e: real seams feed the ledger
# ---------------------------------------------------------------------------


def _make_engine(tmp_path, ledger_path, extra=None):
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10**9,
           "goodput": {"enabled": True, "path": ledger_path},
           "flight_recorder": {"enabled": True, "dump_dir": str(tmp_path)}}
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg,
        rng=jax.random.PRNGKey(3))
    return engine


def _step(engine, i):
    lo = (i % 4) * 8
    loss = engine.forward((X[lo:lo + 8], Y[lo:lo + 8]))
    engine.step()
    return float(loss)


def test_engine_feeds_ledger_and_checkpoint_events_reconcile(tmp_path,
                                                             monkeypatch):
    """A real engine with the ``goodput`` config block: compute +
    recompile accrue from the step seams, the snapshot telescopes, and
    the flight ``checkpoint`` record carries the SAME event_id + dur_s
    as the ledger's durable event row (the reconciliation satellite)."""
    monkeypatch.setenv("DSTPU_RUN_ID", "eng-run")
    flight = get_flight_recorder()
    flight.reset()
    path = str(tmp_path / "runledger.jsonl")
    engine = _make_engine(tmp_path, path)
    gp = get_goodput_ledger()
    try:
        assert gp.enabled and gp.run_id == "eng-run"
        for i in range(3):
            _step(engine, i)
        engine.save_checkpoint(str(tmp_path / "ck"), tag="t0")
        snap = gp.snapshot()
        assert core.telescopes(snap)
        assert snap["categories"]["compute"] > 0.0
        assert snap["categories"]["recompile"] > 0.0
        assert snap["categories"]["checkpoint_save"] > 0.0
        assert snap["tokens"] > 0 and snap["steps"] == 3
        # flight <-> ledger reconciliation by event id
        fl = [e for e in flight.events() if e["kind"] == "checkpoint"]
        assert fl and fl[-1]["op"] == "save" and fl[-1]["dur_s"] > 0.0
        rows = [r for r in core.read_rows(path)
                if r["kind"] == "event" and r["event"] == "checkpoint_save"]
        assert rows, "ledger event row missing for the checkpoint save"
        by_id = {r["event_id"]: r for r in rows}
        led = by_id[fl[-1]["event_id"]]
        assert led["dur_s"] == fl[-1]["dur_s"]
        # the ledger's attributed seconds cover the event's duration
        assert snap["categories"]["checkpoint_save"] >= 0.5 * led["dur_s"]
    finally:
        gp.disable()
        flight.disable()


def test_chaos_kill_restart_rollback_stitches(tmp_path, monkeypatch):
    """THE ISSUE 18 chaos acceptance, in-process: incarnation 0 trains
    + checkpoints and dies (final tick, disable); after a real gap,
    incarnation 1 resumes from the checkpoint, takes a gradient bomb
    through the anomaly skip -> ROLLBACK ladder, and recovers.  The
    stitched jsonl telescopes with nonzero ``restart_downtime``,
    ``rollback``, ``checkpoint_save`` and ``checkpoint_load``."""
    monkeypatch.setenv("DSTPU_RUN_ID", "chaos-run")
    monkeypatch.setenv("DS_SUPERVISOR_RESTART", "0")
    reg = get_registry()
    reg.enable()
    flight = get_flight_recorder()
    flight.reset()
    path = str(tmp_path / "runledger.jsonl")
    ck = tmp_path / "ck"
    anomaly = {"anomaly_detection": {"enabled": True, "factor": 5.0,
                                     "window": 8, "warmup": 3,
                                     "patience": 2, "rollback": True,
                                     "max_rollbacks": 3,
                                     "save_dir": str(ck)}}
    gp = get_goodput_ledger()
    try:
        # -- incarnation 0: train, checkpoint, die ----------------------
        engine = _make_engine(tmp_path, path, extra=anomaly)
        for i in range(5):
            _step(engine, i)
        engine.save_checkpoint(str(ck), tag="good")
        gp.disable()                     # process death: final forced tick
        engine = None

        time.sleep(0.06)                 # the supervisor restart gap

        # -- incarnation 1: restart, resume, bomb -> rollback -----------
        monkeypatch.setenv("DS_SUPERVISOR_RESTART", "1")
        engine = _make_engine(tmp_path, path, extra=anomaly)
        assert gp.enabled and gp.incarnation == 1
        _step(engine, 0)                 # lazy state init (load needs it)
        load_path, _ = engine.load_checkpoint(str(ck), tag="good")
        assert load_path is not None
        for i in range(4):               # arm the detector (warmup=3)
            _step(engine, i)
        rb0 = reg.counter("ds_train_anomaly_rollback_total").value
        with chaos.gradient_bomb(engine, scale=1e18, on_call=1, n=3):
            for i in range(3):
                _step(engine, 5 + i)
        assert reg.counter("ds_train_anomaly_rollback_total").value \
            - rb0 == 1
        _step(engine, 0)                 # post-rollback recovery step
        gp.disable()

        # -- the stitched run -------------------------------------------
        rep = core.stitch(core.read_rows(path), run_id="chaos-run")
        assert len(rep["incarnations"]) == 2
        assert core.telescopes(rep), rep["categories"]
        assert rep["restart_gaps_s"][0] > 0.0
        cats = rep["categories"]
        assert cats["restart_downtime"] > 0.0
        assert cats["rollback"] > 0.0
        assert cats["checkpoint_save"] > 0.0
        assert cats["checkpoint_load"] > 0.0
        assert cats["compute"] > 0.0
        assert rep["goodput_ratio"] > 0.0
        # the offline reader renders the stitched run (both incarnations
        # + the gap line), jax-free
        text = "\n".join(core.render_lines(rep))
        assert "incarnation 0" in text and "incarnation 1" in text
        assert "restart gap 0" in text and "telescopes: True" in text
    finally:
        gp.disable()
        flight.disable()
        reg.disable()
