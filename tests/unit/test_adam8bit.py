"""8-bit blockwise Adam (ops/adam/adam8bit.py): math parity, state memory,
and engine integration with bf16 grad accumulation (the >1B-rung recipe)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.adam.adam8bit import adam8bit


def _run(opt, params, grads_seq):
    state = opt.init(params)
    new_params = getattr(opt, "updates_are_new_params", False)
    for g in grads_seq:
        ups, state = opt.update(g, state, params)
        params = ups if new_params else optax.apply_updates(params, ups)
    return params


def test_small_leaves_match_adamw_exactly():
    # below min_quant_size the moments stay fp32 -> exact AdamW math
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}
    grads_seq = [jax.tree.map(lambda p: jnp.asarray(
        rng.normal(size=p.shape), jnp.float32), params) for _ in range(5)]
    p8 = _run(adam8bit(1e-2, weight_decay=0.01, min_quant_size=10**9),
              params, grads_seq)
    pw = _run(optax.adamw(1e-2, weight_decay=0.01), params, grads_seq)
    for k in params:
        np.testing.assert_allclose(np.asarray(p8[k]), np.asarray(pw[k]),
                                   rtol=1e-5, atol=1e-6)


def test_quantized_path_tracks_adamw():
    # int8 moments introduce bounded error; the resulting trajectory must
    # stay close to fp32 AdamW over several steps
    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(64, 256)) * 0.1, jnp.float32)}
    grads_seq = [{"w": jnp.asarray(rng.normal(size=(64, 256)) * 0.01,
                                   jnp.float32)} for _ in range(10)]
    p8 = _run(adam8bit(1e-3, block=512, min_quant_size=1), params, grads_seq)
    pw = _run(optax.adamw(1e-3), params, grads_seq)
    delta8 = np.asarray(p8["w"] - params["w"]).ravel()
    deltaw = np.asarray(pw["w"] - params["w"]).ravel()
    cos = float(delta8 @ deltaw / (np.linalg.norm(delta8) *
                                   np.linalg.norm(deltaw)))
    assert cos > 0.99, cos
    assert abs(np.linalg.norm(delta8) / np.linalg.norm(deltaw) - 1) < 0.05


def test_state_is_8bit_sized():
    params = {"w": jnp.zeros((1024, 1024), jnp.float32)}
    opt = adam8bit(1e-3, block=512)
    state = opt.init(params)
    state_bytes = sum(x.nbytes for x in jax.tree.leaves(state))
    fp32_state_bytes = 2 * 4 * 1024 * 1024  # fp32 m + v
    # int8 m+v (+ fp32 scales / 512) ~= 0.253x of fp32 states
    assert state_bytes < 0.3 * fp32_state_bytes, state_bytes


def test_stochastic_round_is_unbiased():
    from deepspeed_tpu.ops.adam.adam8bit import stochastic_round_bf16

    x = jnp.asarray(np.random.default_rng(2).normal(size=(4096,)) * 0.1,
                    jnp.float32)
    acc = np.zeros_like(np.asarray(x))
    K = 64
    for i in range(K):
        acc += np.asarray(stochastic_round_bf16(x, jax.random.PRNGKey(i)),
                          np.float32)
    mean = acc / K
    # unbiased: the mean over draws converges to x well below one bf16 ulp
    ulp = np.abs(np.asarray(x)) * 2**-8 + 1e-9
    assert np.all(np.abs(mean - np.asarray(x)) < 0.5 * ulp)


def test_sr_moves_sub_ulp_updates_rtn_stalls():
    """The reason master-free bf16 needs SR: with lr far below one bf16 ulp,
    round-to-nearest never moves the param; stochastic rounding drifts by
    the expected amount."""
    params = {"w": jnp.full((512, 8), 1.0, jnp.bfloat16)}
    g = {"w": jnp.full((512, 8), 1.0, jnp.float32)}  # direction ~= +1

    def run(sr):
        opt = adam8bit(1e-4, weight_decay=0.0, min_quant_size=1,
                       stochastic_rounding=sr)
        st = opt.init(params)
        p = params
        for _ in range(300):
            p, st = opt.update(g, st, p)
        return float(jnp.mean(p["w"].astype(jnp.float32)))

    assert run(False) == 1.0                     # RTN: stuck at 1.0 forever
    drift = 1.0 - run("auto")                    # SR: E[drift] = 300 * lr
    assert 0.5 * 300e-4 < drift < 1.5 * 300e-4, drift


def test_engine_adam8bit_bf16_accum_trains():
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh

    mesh = build_mesh(fsdp=8, devices=jax.devices())
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256)
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 2,
           "bf16": {"enabled": True},
           "data_types": {"grad_accum_dtype": "bf16"},
           "optimizer": {"type": "Adam8bit",
                         "params": {"lr": 3e-3, "min_quant_size": 256}},
           "zero_optimization": {"stage": 1}, "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg, mesh=mesh)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 8, 32), 0, 256)
    losses = [float(engine.train_step((toks, toks))) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    # the persistent accumulator really is bf16
    acc_leaf = jax.tree.leaves(engine.state.grad_acc)[0]
    assert acc_leaf.dtype == jnp.bfloat16


def test_engine_master_free_bf16_trains():
    """bf16.master_weights=false: the persistent state is bf16 (no fp32
    master) and training still converges via stochastic rounding."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh

    mesh = build_mesh(fsdp=8, devices=jax.devices())
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256)
    cfg = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
           "bf16": {"enabled": True, "master_weights": False},
           "data_types": {"grad_accum_dtype": "bf16"},
           "optimizer": {"type": "Adam8bit",
                         "params": {"lr": 3e-3, "min_quant_size": 256}},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg, mesh=mesh)
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8, 32), 0, 256)
    losses = [float(engine.train_step((toks, toks))) for _ in range(10)]
    assert losses[-1] < losses[0], losses
    for leaf in jax.tree.leaves(engine.state.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16


def test_grad_accum_dtype_fp16_rejected():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    with pytest.raises(ValueError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "fp16": {"enabled": True},
                         "data_types": {"grad_accum_dtype": "bf16"}})


def test_xla_fallback_chunked_matches_unchunked(monkeypatch):
    """The xla debug fallback must chunk big leaves (bounded fp32
    temporaries) and produce the same result as the single-chunk path."""
    import deepspeed_tpu.ops.pallas.fused_adam8bit as fab

    block = 64
    nb = 128  # 4 chunks once the bound is shrunk below
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(nb, block), jnp.float32)
    g = jnp.asarray(rng.randn(nb, block), jnp.float32)
    mq = jnp.asarray(rng.randint(-127, 128, (nb, block)), jnp.int8)
    ms = jnp.asarray(np.abs(rng.randn(nb, 1)) * 0.01, jnp.float32)
    vq = jnp.asarray(rng.randint(0, 128, (nb, block)), jnp.int8)
    vs = jnp.asarray(np.abs(rng.randn(nb, 1)) * 0.01, jnp.float32)
    args = dict(b1=0.9, b2=0.999, eps=1e-8, wd=0.01, sr=False, impl="xla")
    c1 = jnp.float32(1.0 / (1 - 0.9))
    c2 = jnp.float32(1.0 / (1 - 0.999))
    lr = jnp.float32(1e-2)
    seed = jnp.int32(7)
    ref = fab.fused_adam8bit_update(p, g, mq, ms, vq, vs, c1, c2, lr, seed, **args)
    monkeypatch.setattr(fab, "XLA_CHUNK_ELEMS", fab.ROW_MULT * block)
    chunked = fab.fused_adam8bit_update(p, g, mq, ms, vq, vs, c1, c2, lr, seed, **args)
    for a, b in zip(ref, chunked):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=1e-6, atol=1e-6)
