"""Generic AutoTP name-analysis classification (VERDICT r3 item 7).

The classifier must produce correct column/row PartitionSpecs for param
trees it has never seen (HF-style naming, unknown custom layers), mirror the
built-in models' hand-written logical_pspecs, and actually shard a no-
logical_pspecs model end-to-end through the engine on a tp mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.module_inject.auto_tp import autotp_pspecs, classify


def test_classify_hf_style_names():
    # column (out-features split, no comm)
    for name in ("q_proj", "k_proj", "v_proj", "up_proj", "gate_proj",
                 "c_attn", "c_fc", "fc1", "query_key_value", "dense_h_to_4h"):
        assert classify(name, 2) == "column", name
    # row (in-features split, all-reduce after)
    for name in ("o_proj", "out_proj", "down_proj", "c_proj", "fc2",
                 "dense_4h_to_h"):
        assert classify(name, 2) == "row", name
    # embeddings split the vocab dim
    for name in ("embed_tokens", "wte", "word_embeddings"):
        assert classify(name, 2) == "embedding", name
    # unknown 2D tensors are left replicated, never guessed
    assert classify("my_custom_linear", 2) == "replicated"
    assert classify("router_gate_matrix", 2) == "replicated"
    # norms/biases replicated unless they belong to a column split
    assert classify("scale", 1) == "replicated"
    assert classify("bq", 1) == "column_bias"


def test_autotp_pspecs_unseen_tree():
    """An arbitrary HF-shaped tree (names the framework's models never use)
    gets the Megatron layout."""
    D, F, V = 8, 16, 32
    tree = {
        "embed_tokens": {"weight": np.zeros((V, D))},
        "h": {
            "attn": {"q_proj": {"weight": np.zeros((D, D)),
                                "bias": np.zeros((D,))},
                     "out_proj": {"weight": np.zeros((D, D)),
                                  "bias": np.zeros((D,))}},
            "mlp": {"fc1": {"weight": np.zeros((D, F))},
                    "fc2": {"weight": np.zeros((F, D))}},
            "ln": {"weight": np.zeros((D,))},
            "mystery_proj": {"weight": np.zeros((D, D))},
        },
    }
    specs = autotp_pspecs(tree)
    assert specs["embed_tokens"]["weight"] == P("tp", None)
    assert specs["h"]["attn"]["q_proj"]["weight"] == P(None, "tp")
    assert specs["h"]["attn"]["q_proj"]["bias"] == P("tp")
    assert specs["h"]["attn"]["out_proj"]["weight"] == P("tp", None)
    assert specs["h"]["attn"]["out_proj"]["bias"] == P(None)
    assert specs["h"]["mlp"]["fc1"]["weight"] == P(None, "tp")
    assert specs["h"]["mlp"]["fc2"]["weight"] == P("tp", None)
    assert specs["h"]["ln"]["weight"] == P(None)
    assert specs["h"]["mystery_proj"]["weight"] == P(None, None)


def test_autotp_matches_builtin_logical_pspecs():
    """On the built-in CausalLM tree the classifier must agree with the
    hand-written logical_pspecs for every 2D+ weight."""
    from deepspeed_tpu.models import causal_lm

    model = causal_lm("llama-tiny", num_layers=2, hidden_size=32,
                      intermediate_size=64, num_heads=4, num_kv_heads=2,
                      vocab_size=128, max_seq_len=32)
    params = model.init(jax.random.PRNGKey(0))
    want = model.logical_pspecs()
    got = autotp_pspecs(params)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(got)
    for (pw, sw), (pg, sg) in zip(flat_w, flat_g):
        assert pw == pg
        assert tuple(sw) == tuple(sg), (jax.tree_util.keystr(pw), sw, sg)


def test_engine_autotp_fallback_shards(rng):
    """A model with params but no logical_pspecs trains on a tp=2 mesh with
    AutoTP-derived shardings actually applied."""
    devs = jax.devices()[:4]
    mesh = build_mesh(tp=2, devices=devs)
    set_global_mesh(mesh)

    D, F, V = 16, 32, 64

    class NoSpecModel:
        def init(self, rng, *a):
            k = jax.random.split(rng, 3)
            return {
                "embed_tokens": jax.random.normal(k[0], (V, D)) * 0.02,
                "fc1": {"weight": jax.random.normal(k[1], (D, F)) * 0.1,
                        "bias": jnp.zeros((F,))},
                "fc2": {"weight": jax.random.normal(k[2], (F, V)) * 0.1},
            }

        def apply(self, params, toks, labels=None, rngs=None):
            x = jnp.take(params["embed_tokens"], toks, axis=0)
            h = jax.nn.relu(x @ params["fc1"]["weight"] + params["fc1"]["bias"])
            logits = h @ params["fc2"]["weight"]
            if labels is None:
                return logits
            lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), -1)
            gold = jnp.take_along_axis(logits.astype(jnp.float32),
                                       labels[..., None], -1).squeeze(-1)
            return (lse - gold).mean()

    cfg = {"train_batch_size": 4, "train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=NoSpecModel(), config=cfg,
                                               mesh=mesh, rng=rng)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, V)
    losses = []
    for _ in range(5):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # the AutoTP classification was applied: fc1 out-dim is tp-split
    spec = engine._param_specs["fc1"]["weight"]
    assert "tp" in tuple(spec), spec
    emb_spec = engine._param_specs["embed_tokens"]
    assert tuple(emb_spec)[0] == "tp", emb_spec
