"""ZeRO-Offload / ZeRO-Infinity tests.

Reference analog: ``tests/unit/runtime/zero/test_zero_offloadpp.py`` +
swap-tensor suites (SURVEY.md §4): offload numerics must match the in-device
optimizer, NVMe states must round-trip, and the device must provably hold no
optimizer state.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm


def _train(devices, rng, offload_device=None, nvme_path=None, steps=8,
           stage=2, accum=1):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, max_seq_len=64)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    zero = {"stage": stage}
    if offload_device:
        zero["offload_optimizer"] = {"device": offload_device,
                                     **({"nvme_path": nvme_path} if nvme_path else {})}
    cfg = {"train_micro_batch_size_per_gpu": 1,  # global micro 8 over 8-way mesh
           "gradient_accumulation_steps": accum,
           "bf16": {"enabled": True},
           "zero_optimization": zero,
           "optimizer": {"type": "AdamW",
                         "params": {"lr": 1e-2, "weight_decay": 0.01}},
           "gradient_clipping": 1.0,
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg, mesh=mesh)
    losses = []
    for _ in range(steps):
        for _ in range(accum):
            loss = engine.forward((toks, toks))
            engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return engine, losses


def test_cpu_offload_matches_device_optimizer(devices, rng):
    """offload_optimizer.device=cpu trains with the same numerics as the
    in-device AdamW (fp32 master on host vs fp32 master on device)."""
    _, base = _train(devices, rng)
    _, off = _train(devices, rng, offload_device="cpu")
    np.testing.assert_allclose(off, base, rtol=2e-3, atol=2e-3)
    assert off[-1] < off[0]


def test_cpu_offload_device_holds_no_optimizer_state(devices, rng):
    """The ZeRO-Offload memory contract: no fp32 master or moments in HBM."""
    engine, _ = _train(devices, rng, offload_device="cpu", steps=2)
    # device optimizer state is empty
    assert not jax.tree_util.tree_leaves(engine.state.opt_state)
    # device params are the compute dtype (bf16), not fp32 masters
    for leaf in jax.tree_util.tree_leaves(engine.state.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, leaf.dtype
    # host masters exist and are fp32
    assert engine._offload_opt is not None
    for m in engine._offload_opt.masters():
        assert m.dtype == np.float32


def test_cpu_offload_not_silently_ignored(devices, rng):
    engine, _ = _train(devices, rng, offload_device="cpu", steps=1)
    assert engine._offload and engine._offload_device == "cpu"


def test_nvme_offload_roundtrip(devices, rng, tmp_path):
    """device=nvme: states stream through aio files and training matches the
    cpu-offload trajectory."""
    _, cpu_losses = _train(devices, rng, offload_device="cpu")
    engine, nvme_losses = _train(devices, rng, offload_device="nvme",
                                 nvme_path=str(tmp_path / "swap"))
    np.testing.assert_allclose(nvme_losses, cpu_losses, rtol=1e-5, atol=1e-6)
    files = os.listdir(str(tmp_path / "swap"))
    assert files and all(f.startswith("state_") for f in files)
    # state files hold [master, m, v] fp32: nonzero moments after training
    sw = engine._offload_opt._swapper
    buf = sw.read_sync(0)
    sz = engine._offload_opt._sizes[0]
    assert np.abs(buf[sz:2 * sz]).max() > 0  # exp_avg moved


def test_offload_checkpoint_resume(devices, rng, tmp_path):
    """save/load restores host masters + moments (training-resume parity)."""
    engine, _ = _train(devices, rng, offload_device="cpu", steps=4)
    engine.save_checkpoint(str(tmp_path))
    m_before = [m.copy() for m in engine._offload_opt.masters()]
    step_before = engine._offload_opt.step_count

    engine2, _ = _train(devices, rng, offload_device="cpu", steps=1)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2._offload_opt.step_count == step_before
    for a, b in zip(engine2._offload_opt.masters(), m_before):
        np.testing.assert_array_equal(a, b)


def test_offload_with_grad_accumulation(devices, rng):
    _, losses = _train(devices, rng, offload_device="cpu", steps=4, accum=2)
    assert losses[-1] < losses[0]


class TestOffloadOptFamilies:
    """CPU Adagrad/Lion reachable from the offload path (VERDICT r2 row 50)."""

    @pytest.mark.parametrize("opt", ["Adagrad", "Lion"])
    def test_offload_family_trains(self, opt):
        from tests.unit.simple_model import SimpleModel, random_dataset

        x, y = random_dataset(n=16)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": opt, "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 1,
                                     "offload_optimizer": {"device": "cpu"}}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16), config=cfg,
            rng=jax.random.PRNGKey(0))
        losses = []
        for _ in range(10):
            loss = engine.forward((x[:8], y[:8]))
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert engine._offload_opt.opt_type == opt.lower()

    @pytest.mark.parametrize("opt", ["Adagrad", "Lion"])
    def test_native_matches_numpy(self, opt):
        import numpy as np

        if opt == "Adagrad":
            from deepspeed_tpu.ops.adagrad import DeepSpeedCPUAdagrad as Cls
        else:
            from deepspeed_tpu.ops.lion import DeepSpeedCPULion as Cls
        rng = np.random.default_rng(0)
        p0 = rng.standard_normal(300).astype(np.float32)
        g = rng.standard_normal(300).astype(np.float32)
        nat = Cls(params=[p0.copy()], lr=1e-2, weight_decay=0.01)
        ref = Cls(params=[p0.copy()], lr=1e-2, weight_decay=0.01)
        ref._native = None
        for _ in range(3):
            nat.step([g])
            ref.step([g])
        np.testing.assert_allclose(nat.params[0], ref.params[0],
                                   rtol=1e-5, atol=1e-6)
