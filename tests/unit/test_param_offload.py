"""ZeRO-Infinity parameter tiering tests (VERDICT r2 item 3).

Params live in pinned host memory; the model streams each scanned layer to
the device inside the forward; grads come back host-resident and accumulate
in numpy; the host optimizer steps them.  No device-resident [model]-sized
buffer exists at any point.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm


def _engine(stage=3, gas=1, offload_param=True, mesh=None):
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=4, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, max_seq_len=64, remat=False)
    zero = {"stage": stage, "offload_optimizer": {"device": "cpu"}}
    if offload_param:
        zero["offload_param"] = {"device": "cpu"}
    cfg = {"train_batch_size": 8 * gas, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": gas,
           "bf16": {"enabled": True},
           "zero_optimization": zero,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "gradient_clipping": 1.0, "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               mesh=mesh, rng=jax.random.PRNGKey(5))
    return engine


def test_params_host_resident_and_training(mesh8, rng):
    set_global_mesh(mesh8)
    engine = _engine(mesh=mesh8)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    losses = []
    for _ in range(6):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # host placement is capability-gated: pinned_host where the backend
    # advertises it, its host-side kind otherwise (this jax's CPU client
    # only has unpinned_host — which is still the host-resident contract)
    from deepspeed_tpu.accelerator.real_accelerator import host_memory_kind

    expected = host_memory_kind()
    if expected is None:
        pytest.skip("backend exposes no memory-kind API")
    for leaf in jax.tree.leaves(engine.state.params):
        assert leaf.sharding.memory_kind == expected, leaf.sharding
    # no device-resident grad accumulator exists at all
    assert engine.state.grad_acc == ()


def test_device_window_bounded(mesh8, rng):
    """The compiled fwd+bwd must not materialize the whole host-resident
    param tree on device: temp memory stays well under 3x param bytes
    (activations dominate; the [L,...] stacks never appear)."""
    set_global_mesh(mesh8)
    engine = _engine(mesh=mesh8)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    loss = engine.forward((toks, toks))  # builds state + compiles
    engine.step()
    n_param_bytes = sum(l.size * l.dtype.itemsize
                        for l in jax.tree.leaves(engine.state.params))
    from deepspeed_tpu.runtime.dataloader import shard_batch

    batch = shard_batch((toks, toks), engine.mesh)
    lowered = engine._pofwdbwd_fn.lower(engine.state.params, batch,
                                        jax.random.PRNGKey(0))
    ma = lowered.compile().memory_analysis()
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("backend exposes no memory analysis")
    # generous bound: whole-tree materialization would add ~2x param bytes
    # (params + grads) on top of activations; the streamed path stays below
    assert ma.temp_size_in_bytes < 16 * n_param_bytes  # smoke bound on CPU
    assert float(loss) > 0


def test_matches_plain_offload(mesh8, rng):
    """offload_param training must match plain optimizer-offload numerically
    (same CPUAdam, same bf16 compute params)."""
    set_global_mesh(mesh8)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    outs = {}
    for name, po in (("plain", False), ("tiered", True)):
        engine = _engine(offload_param=po, mesh=mesh8, gas=2)
        for _ in range(2):
            for _ in range(2):
                engine.forward((toks, toks))
            engine.step()
        outs[name] = jax.device_get(engine.state.params)
    for a, b in zip(jax.tree.leaves(outs["plain"]), jax.tree.leaves(outs["tiered"])):
        # tolerance: a couple of bf16 ULPs — host-side vs device-side clip
        # ordering legitimately flips the last bit on isolated elements
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=4e-2, atol=1.6e-2)


def test_grad_streaming_device_window(mesh8, rng):
    """VERDICT r3 item 2: a model whose params+grads together exceed a
    synthetic HBM budget still trains, because the streamed per-layer
    programs never hold a [model]-sized buffer.  Each segment's device
    footprint (args + temps + outputs) must stay under total param bytes —
    the whole-tree fwd+bwd needs ~2x param bytes (params + grads) and would
    blow the same budget."""
    set_global_mesh(mesh8)
    model = causal_lm("llama-tiny", mesh=mesh8, num_layers=8, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, max_seq_len=64, remat=False)
    cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "bf16": {"enabled": True},
           "zero_optimization": {"stage": 3,
                                 "offload_optimizer": {"device": "cpu"},
                                 "offload_param": {"device": "cpu"}},
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "gradient_clipping": 1.0, "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               mesh=mesh8,
                                               rng=jax.random.PRNGKey(5))
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    losses = []
    for _ in range(4):
        loss = engine.forward((toks, toks))
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert engine._streamed is not None, "streamed grad path not active"
    n_param_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                        for a in jax.tree.leaves(engine._np_params))
    assert engine._streamed.probes, "no segment probes recorded"
    for name, (fn, spec) in engine._streamed.probes.items():
        ma = fn.lower(*spec).compile().memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend exposes no memory analysis")
        window = (ma.temp_size_in_bytes + ma.argument_size_in_bytes
                  + ma.output_size_in_bytes)
        # per-layer window: <= ~2 layers of params + activations << model
        assert window < n_param_bytes, (name, window, n_param_bytes)


def test_checkpoint_roundtrip_param_offload(tmp_path, mesh8, rng):
    set_global_mesh(mesh8)
    engine = _engine(mesh=mesh8)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    engine.forward((toks, toks))
    engine.step()
    engine.save_checkpoint(str(tmp_path), tag="t")
    saved = jax.device_get(engine.state.params)

    other = _engine(mesh=mesh8)
    other.forward((toks, toks))
    other.step()
    other.load_checkpoint(str(tmp_path), tag="t")
    for a, b in zip(jax.tree.leaves(saved), jax.tree.leaves(jax.device_get(other.state.params))):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_streamed_matches_whole_program_parallel_residual(mesh8, rng):
    """The streamed segments reuse the model's _layer, so new architectures
    (gpt-neox parallel residual + partial rope) must produce the same
    training trajectory streamed as through the whole-program fwd/bwd."""
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    outs = {}
    for name, stream in (("whole", False), ("streamed", True)):
        set_global_mesh(mesh8)
        model = causal_lm("llama-tiny", mesh=mesh8, num_layers=3,
                          hidden_size=64, intermediate_size=128, num_heads=4,
                          num_kv_heads=4, vocab_size=256, max_seq_len=64,
                          remat=False, parallel_residual=True, rotary_pct=0.5,
                          norm="layernorm", use_bias=True)
        cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
               "gradient_accumulation_steps": 1, "bf16": {"enabled": True},
               "zero_optimization": {
                   "stage": 3, "offload_optimizer": {"device": "cpu"},
                   "offload_param": {"device": "cpu",
                                     "stream_grads": stream}},
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
               "gradient_clipping": 1.0, "steps_per_print": 10**9}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=cfg, mesh=mesh8, rng=jax.random.PRNGKey(5))
        losses = []
        for _ in range(3):
            loss = engine.forward((toks, toks))
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0], (name, losses)
        outs[name] = jax.device_get(engine.state.params)
    for a, b in zip(jax.tree.leaves(outs["whole"]),
                    jax.tree.leaves(outs["streamed"])):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=4e-2, atol=1.6e-2)
