"""Fused decode kernel parity (VERDICT r4 item 1: the Pallas decode path).

Each kernel is checked in interpret mode against its jnp reference on the
8-device CPU backend, over the feature matrix the model zoo exercises
(layernorm/rmsnorm, bias/no-bias, GLU/plain MLP, GQA, parallel residual,
position edge cases for the length-aware attention)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.decode import (
    _flash_decode_ref, _mlp_ref, _norm_qkv_ref, _proj_norm_ref,
    flash_decode, fused_mlp, fused_norm_qkv, fused_proj_norm)


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype) * 0.5


@pytest.mark.parametrize("kind", ["layernorm", "rmsnorm"])
@pytest.mark.parametrize("with_bias", [True, False])
def test_norm_qkv_parity(kind, with_bias):
    B, D, N = 2, 256, 768
    x = _rand(0, B, D)
    scale = 1.0 + 0.1 * _rand(1, D)
    bias = _rand(2, D)
    w = _rand(3, D, N)
    bq = _rand(4, N) if with_bias else None
    got = fused_norm_qkv(x, scale, bias, w, bq, kind=kind, eps=1e-5,
                         impl="interpret")
    want = _norm_qkv_ref(x, scale, bias, w, bq, kind=kind, eps=1e-5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_norm_qkv_blocked_grid():
    """N large enough to split into several column blocks."""
    B, D, N = 1, 2048, 6144
    x = _rand(0, B, D, dtype=jnp.bfloat16)
    scale = jnp.ones((D,), jnp.bfloat16)
    bias = jnp.zeros((D,), jnp.bfloat16)
    w = _rand(1, D, N, dtype=jnp.bfloat16)
    got = fused_norm_qkv(x, scale, bias, w, None, kind="rmsnorm",
                         impl="interpret")
    want = _norm_qkv_ref(x, scale, bias, w, None, kind="rmsnorm", eps=1e-5)
    np.testing.assert_allclose(np.float32(got), np.float32(want),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("pos", [0, 5, 255, 256, 300, 767])
@pytest.mark.parametrize("rep", [1, 4])
def test_flash_decode_positions(pos, rep):
    """Length-aware masking at block boundaries, GQA included."""
    B, Hkv, Smax, Dh = 2, 3, 768, 64
    H = Hkv * rep
    q = _rand(0, B, H, Dh)
    k = _rand(1, B, Hkv, Smax, Dh)
    v = _rand(2, B, Hkv, Smax, Dh)
    got = flash_decode(q, k, v, pos, impl="interpret")
    want = _flash_decode_ref(q, k, v, jnp.int32(pos), scale=Dh ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def _paged_from_logical(k, v, maxp, page, seed=7):
    """Scatter a logical [B, Hkv, maxp*page, Dh] cache into a paged pool
    [P, Hkv, page, Dh] under a SHUFFLED page assignment (page 0 = junk)."""
    B, Hkv, Smax, Dh = k.shape
    assert Smax == maxp * page
    P = B * maxp + 1
    order = np.random.RandomState(seed).permutation(B * maxp) + 1
    pt = order.reshape(B, maxp).astype(np.int32)
    kp = np.zeros((P, Hkv, page, Dh), np.float32)
    vp = np.zeros((P, Hkv, page, Dh), np.float32)
    for b in range(B):
        for j in range(maxp):
            kp[pt[b, j]] = np.asarray(k[b, :, j * page:(j + 1) * page])
            vp[pt[b, j]] = np.asarray(v[b, :, j * page:(j + 1) * page])
    return jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pt)


@pytest.mark.parametrize("pos", [[5, 300], [255, 256], [767, 0]])
@pytest.mark.parametrize("alibi", [False, True])
def test_flash_decode_paged_matches_logical(pos, alibi):
    """The page-table-indirected index map must reproduce the contiguous
    kernel exactly: a shuffled physical page assignment with per-row
    positions (and per-row DMA clamps) against the dense reference over
    the logical view."""
    B, Hkv, rep, Dh, page, maxp = 2, 2, 2, 64, 256, 3
    H = Hkv * rep
    q = _rand(0, B, H, Dh)
    k = _rand(1, B, Hkv, maxp * page, Dh)
    v = _rand(2, B, Hkv, maxp * page, Dh)
    kp, vp, pt = _paged_from_logical(k, v, maxp, page)
    posv = jnp.asarray(pos, jnp.int32)
    got = flash_decode(q, kp, vp, posv, page_table=pt, alibi=alibi,
                       impl="interpret")
    want = _flash_decode_ref(q, k, v, posv, scale=Dh ** -0.5, alibi=alibi)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_decode_paged_layer_stacked():
    """decode_step reads the stacked [L, P, Hkv, page, Dh] pool at a
    static layer offset through the index map — no slice materializes."""
    B, Hkv, Dh, page, maxp, L = 2, 2, 64, 256, 2, 2
    ks, vs, pools = [], [], []
    for l in range(L):
        k = _rand(10 + l, B, Hkv, maxp * page, Dh)
        v = _rand(20 + l, B, Hkv, maxp * page, Dh)
        kp, vp, pt = _paged_from_logical(k, v, maxp, page, seed=3)
        ks.append(k); vs.append(v); pools.append((kp, vp))
    kp_all = jnp.stack([p[0] for p in pools])
    vp_all = jnp.stack([p[1] for p in pools])
    q = _rand(0, B, Hkv, Dh)
    posv = jnp.asarray([300, 511], jnp.int32)
    for l in range(L):
        got = flash_decode(q, kp_all, vp_all, posv, layer=l, page_table=pt,
                           impl="interpret")
        want = _flash_decode_ref(q, ks[l], vs[l], posv, scale=Dh ** -0.5)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_decode_paged_small_page_falls_back():
    """Pages below the 128-lane tile route to the gathered dense
    reference (the CPU / tiny-config path) — and still match."""
    B, Hkv, Dh, page, maxp = 1, 2, 64, 16, 4
    q = _rand(0, B, Hkv, Dh)
    k = _rand(1, B, Hkv, maxp * page, Dh)
    v = _rand(2, B, Hkv, maxp * page, Dh)
    kp, vp, pt = _paged_from_logical(k, v, maxp, page)
    got = flash_decode(q, kp, vp, jnp.asarray([33], jnp.int32),
                       page_table=pt, impl="interpret")
    want = _flash_decode_ref(q, k, v, jnp.asarray([33], jnp.int32),
                             scale=Dh ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_decode_odd_cache_falls_back():
    """Cache lengths that are not a block multiple route to the dense
    reference (a non-tile-aligned Pallas block would be handed to Mosaic
    otherwise) — and still produce the right numbers."""
    B, Hkv, Smax, Dh = 1, 2, 145, 64
    q = _rand(0, B, Hkv, Dh)
    k = _rand(1, B, Hkv, Smax, Dh)
    v = _rand(2, B, Hkv, Smax, Dh)
    got = flash_decode(q, k, v, 100, impl="interpret")
    want = _flash_decode_ref(q, k, v, jnp.int32(100), scale=Dh ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_short_generation_small_cache():
    """A default-sized generate (cache under one decode block) works on the
    fused path end-to-end (exercises the odd-Smax fallback in situ)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    model = causal_lm("llama-tiny", num_layers=2, vocab_size=256,
                      max_seq_len=64)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model, config={"max_out_tokens": 64, "dtype": "float32"})
    engine.set_params(params)
    assert engine._dparams is not None
    out = np.asarray(engine.generate(np.array([[3, 1, 4]]),
                                     max_new_tokens=12, do_sample=False))
    assert out.shape == (1, 15)


@pytest.mark.parametrize("pos", [5, 300])
def test_flash_decode_alibi(pos):
    """ALiBi bias in the decode kernel matches the biased dense reference."""
    B, Hkv, Smax, Dh = 1, 6, 512, 64   # 6 heads: non-power-of-2 slopes
    q = _rand(0, B, Hkv, Dh)
    k = _rand(1, B, Hkv, Smax, Dh)
    v = _rand(2, B, Hkv, Smax, Dh)
    got = flash_decode(q, k, v, pos, alibi=True, impl="interpret")
    want = _flash_decode_ref(q, k, v, jnp.int32(pos), scale=Dh ** -0.5,
                             alibi=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_decode_stacked_layer_offset():
    """layer= reads the right slice of a stacked [L, B, Hkv, Smax, Dh]
    cache through the index-map offset."""
    L, B, Hkv, Smax, Dh = 3, 2, 2, 512, 64
    q = _rand(0, B, 2 * Hkv, Dh)
    k = _rand(1, L, B, Hkv, Smax, Dh)
    v = _rand(2, L, B, Hkv, Smax, Dh)
    for l in range(L):
        got = flash_decode(q, k, v, 300, layer=l, impl="interpret")
        want = _flash_decode_ref(q, k[l], v[l], jnp.int32(300),
                                 scale=Dh ** -0.5)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("kind,parallel", [("layernorm", False),
                                           ("rmsnorm", False),
                                           ("layernorm", True)])
def test_proj_norm_parity(kind, parallel):
    B, M, D = 2, 192, 256
    ctx = _rand(0, B, M)
    resid = _rand(1, B, D)
    wo = _rand(2, M, D)
    bo = _rand(3, D)
    scale = 1.0 + 0.1 * _rand(4, D)
    bias = _rand(5, D)
    got_r, got_h = fused_proj_norm(ctx, resid, wo, bo, scale, bias,
                                   kind=kind, parallel=parallel,
                                   impl="interpret")
    want_r, want_h = _proj_norm_ref(ctx, resid, wo, bo, scale, bias,
                                    kind=kind, eps=1e-5, parallel=parallel)
    np.testing.assert_allclose(got_r, want_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(got_h, want_h, rtol=2e-5, atol=2e-5)


def _generate(preset, fused, prompt, dtype="float32", unroll=4, **overrides):
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    model = causal_lm(preset, **overrides)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model, config={"max_out_tokens": 128, "dtype": dtype,
                       "use_fused_decode": fused, "decode_unroll": unroll})
    engine.set_params(params)
    if fused:
        assert engine._dparams is not None, "injection should be active"
    else:
        assert engine._dparams is None
    return np.asarray(engine.generate(prompt, max_new_tokens=24,
                                      do_sample=False))


@pytest.mark.parametrize("preset,overrides", [
    ("gpt2-small", dict(num_layers=2, hidden_size=128, num_heads=4,
                        vocab_size=512, max_seq_len=128)),
    ("llama-tiny", dict(num_layers=2, vocab_size=512, max_seq_len=128)),
])
def test_fused_generation_matches_unfused(preset, overrides):
    """Kernel-injected decode produces the same greedy tokens as the
    reference-shaped unfused loop (end-to-end injection parity, the
    containers-level check the other import families get)."""
    prompt = np.array([[5, 17, 200, 3, 42, 7, 11, 23]])
    plain = _generate(preset, False, prompt, **overrides)
    fused = _generate(preset, True, prompt, **overrides)
    np.testing.assert_array_equal(plain, fused)


def test_int8_kernels_match_refs():
    """In-kernel dequant (wscale=...) matches the reference path that
    dequantizes before the matmul, for all three weight-bearing kernels."""
    from deepspeed_tpu.models.quant import quantize_weight

    B, D, N, F = 2, 256, 384, 512
    x = _rand(0, B, D)
    scale = 1.0 + 0.1 * _rand(1, D)
    bias = _rand(2, D)
    wq = quantize_weight(_rand(3, D, N))
    got = fused_norm_qkv(x, scale, bias, wq.q, None, kind="layernorm",
                         wscale=wq.scale, impl="interpret")
    want = _norm_qkv_ref(x, scale, bias, wq.astype(x.dtype), None,
                         kind="layernorm", eps=1e-5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    ctx = _rand(4, B, N)
    wo = quantize_weight(_rand(5, N, D))
    got_r, got_h = fused_proj_norm(ctx, x, wo.q, None, scale, bias,
                                   kind="layernorm", wscale=wo.scale,
                                   impl="interpret")
    want_r, want_h = _proj_norm_ref(ctx, x, wo.astype(x.dtype), None, scale,
                                    bias, kind="layernorm", eps=1e-5,
                                    parallel=False)
    np.testing.assert_allclose(got_r, want_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(got_h, want_h, rtol=2e-5, atol=2e-5)

    wu = quantize_weight(_rand(6, D, F))
    wg = quantize_weight(_rand(7, D, F))
    wd = quantize_weight(_rand(8, F, D))
    got = fused_mlp(x, x, wu.q, wd.q, wg.q, act="silu",
                    wscales=(wu.scale, wg.scale, wd.scale),
                    impl="interpret")
    want = _mlp_ref(x, x, wu.astype(x.dtype), wg.astype(x.dtype),
                    wd.astype(x.dtype), None, None, None, act="silu")
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)


def test_int8_weights_fused_generation():
    """int8 weight serving rides the kernel-injected path (dequant
    in-kernel) and matches the unfused int8 loop."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    outs = {}
    for fused in (True, False):
        model = causal_lm("llama-tiny", num_layers=2, vocab_size=512,
                          max_seq_len=512)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        engine = deepspeed_tpu.init_inference(
            model, config={"max_out_tokens": 512, "dtype": "int8",
                           "use_fused_decode": fused})
        engine.set_params(params)
        assert (engine._dparams is not None) == fused
        outs[fused] = np.asarray(engine.generate(
            np.array([[5, 17, 200, 3]]), max_new_tokens=280,
            do_sample=False))
    agree = (outs[True] == outs[False]).mean()
    assert agree > 0.9, agree                     # bf16 reorder tolerance
    np.testing.assert_array_equal(outs[True][:, :12], outs[False][:, :12])


def test_unroll_tail_exact():
    """decode_unroll > 1 must not change the produced token count or the
    tokens themselves when max_new_tokens is not a multiple of the unroll."""
    overrides = dict(num_layers=2, hidden_size=128, num_heads=4,
                     vocab_size=512, max_seq_len=128)
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    outs = []
    for unroll in (1, 3):
        model = causal_lm("gpt2-small", **overrides)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        engine = deepspeed_tpu.init_inference(
            model, config={"max_out_tokens": 64, "dtype": "float32",
                           "use_fused_decode": False,
                           "decode_unroll": unroll})
        engine.set_params(params)
        outs.append(np.asarray(engine.generate(
            np.array([[5, 17, 200]]), max_new_tokens=7, do_sample=False)))
    assert outs[0].shape == outs[1].shape == (1, 10)
    np.testing.assert_array_equal(outs[0], outs[1])


@pytest.mark.parametrize("glu", [True, False])
@pytest.mark.parametrize("with_bias", [True, False])
def test_mlp_parity(glu, with_bias):
    B, D, F = 2, 256, 1024
    h = _rand(0, B, D)
    r = _rand(1, B, D)
    w_up = _rand(2, D, F)
    w_gate = _rand(3, D, F) if glu else None
    w_down = _rand(4, F, D)
    b_up = _rand(5, F) if with_bias else None
    b_gate = _rand(6, F) if (glu and with_bias) else None
    b_down = _rand(7, D) if with_bias else None
    act = "silu" if glu else "gelu"
    got = fused_mlp(h, r, w_up, w_down, w_gate, b_up, b_gate, b_down,
                    act=act, impl="interpret")
    want = _mlp_ref(h, r, w_up, w_gate, w_down, b_up, b_gate, b_down, act=act)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)
