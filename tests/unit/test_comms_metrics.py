"""Training-side comm accounting (monitor/comms.py) + FLOPs/MFU + HBM
telemetry: byte/bandwidth golden values, the disabled-path cost contract
(one branch, no allocation), quantized-collective series, and the
acceptance smoke — a ZeRO-3 training run with telemetry on exposes nonzero
``ds_comm_all_gather_*`` bytes/latency and a ``ds_train_mfu`` gauge via
``/statz``, while disabling telemetry is loss-identical."""

import json
import sys
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.monitor.comms import CommMetrics, busbw_factor, comm_metrics
from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry


# ---------------------------------------------------------------------------
# bandwidth / byte math golden values
# ---------------------------------------------------------------------------


def test_busbw_factor_golden():
    # NCCL-tests ring factors at P=8
    assert busbw_factor("all_reduce", 8) == pytest.approx(2 * 7 / 8)
    assert busbw_factor("compressed_allreduce", 8) == pytest.approx(2 * 7 / 8)
    assert busbw_factor("all_gather", 8) == pytest.approx(7 / 8)
    assert busbw_factor("reduce_scatter", 8) == pytest.approx(7 / 8)
    assert busbw_factor("q_reduce_scatter", 8) == pytest.approx(7 / 8)
    assert busbw_factor("all_to_all", 8) == pytest.approx(7 / 8)
    assert busbw_factor("zpp_q_all_gather_hpz", 4) == pytest.approx(3 / 4)
    assert busbw_factor("ppermute", 8) == 1.0
    assert busbw_factor("broadcast", 8) == 1.0
    # a world of one moves nothing over links
    assert busbw_factor("all_reduce", 1) == 1.0


def test_trace_time_record_bytes_and_dtype_label():
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    cm.record("all_gather", "fsdp", jnp.zeros((4, 4), jnp.float32))  # 64 B
    cm.record("all_gather", "fsdp", jnp.zeros((8,), jnp.bfloat16))   # 16 B
    assert reg.get("ds_comm_all_gather_calls_total").value == 2
    assert reg.get("ds_comm_all_gather_bytes_total",
                   labels={"dtype": "float32"}).value == 64
    assert reg.get("ds_comm_all_gather_bytes_total",
                   labels={"dtype": "bfloat16"}).value == 16
    # the back-compat dict ledger records the same volume per op@axis
    assert cm.bytes["all_gather@fsdp"] == 80
    assert cm.counts["all_gather@fsdp"] == 2


def test_commit_bandwidth_golden():
    """8 GB moved in a 1.0s window at P=8: algbw == 8 GB/s exactly,
    busbw == algbw * (P-1)/P for all_gather."""
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    cm.commit([("all_gather", 3, 8_000_000_000, "float32", 8)], seconds=1.0)
    assert reg.get("ds_comm_all_gather_calls_total").value == 3
    assert reg.get("ds_comm_all_gather_bytes_total",
                   labels={"dtype": "float32"}).value == 8_000_000_000
    assert reg.get("ds_comm_all_gather_algbw_gbps").value == pytest.approx(8.0)
    assert reg.get("ds_comm_all_gather_busbw_gbps").value == pytest.approx(7.0)
    h = reg.get("ds_comm_all_gather_seconds")
    assert h.count == 1 and h.sum == pytest.approx(1.0)
    # two ops sharing one window: latency attribution is byte-weighted
    cm.commit([("all_gather", 1, 3_000_000, "float32", 8),
               ("reduce_scatter", 1, 1_000_000, "float32", 8)], seconds=0.4)
    assert reg.get("ds_comm_all_gather_seconds").sum == pytest.approx(1.3)
    assert reg.get("ds_comm_reduce_scatter_seconds").sum == pytest.approx(0.1)


def test_eager_span_records_latency():
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    with cm.span("broadcast", 1024, "uint8", world=4):
        pass
    h = reg.get("ds_comm_broadcast_seconds")
    assert h.count == 1 and h.sum > 0
    assert reg.get("ds_comm_broadcast_calls_total").value == 1
    assert reg.get("ds_comm_broadcast_busbw_gbps").value == \
        reg.get("ds_comm_broadcast_algbw_gbps").value  # factor 1.0


def test_disabled_path_no_accounting_no_allocation():
    """While comm accounting is off, record()/commit()/span() are one
    branch and allocate nothing (PR 2's no-alloc assertion style)."""
    reg = MetricsRegistry()                      # disabled
    cm = CommMetrics(registry=reg)               # disabled
    x = np.zeros((4, 4), np.float32)
    entries = [("all_gather", 1, 64, "float32", 8)]
    cm.record("all_gather", "fsdp", x)           # warm any lazy machinery
    cm.commit(entries, 0.1)
    before = sys.getallocatedblocks()
    for _ in range(5000):
        cm.record("all_gather", "fsdp", x)
        cm.commit(entries, 0.1)
    delta = sys.getallocatedblocks() - before
    assert delta < 100, f"disabled comm accounting allocated {delta} blocks"
    assert not cm.counts and not cm.bytes
    assert reg.get("ds_comm_all_gather_calls_total") is None
    # enabled comm logger + DISABLED registry: dict ledger only, and the
    # registry instruments created must still record nothing
    cm.configure(enabled=True)
    cm.record("all_gather", "fsdp", x)
    assert cm.counts["all_gather@fsdp"] == 1
    inst = reg.get("ds_comm_all_gather_calls_total")
    assert inst is None or inst.value == 0


def test_quantized_collective_series_present(mesh8):
    """Tracing the quantized ZeRO++ collectives lands their ds_comm_q_*
    series in the registry (eval_shape traces without compiling)."""
    from deepspeed_tpu.runtime.comm.quantized import (quantized_all_gather,
                                                      quantized_reduce_scatter)

    reg = get_registry()
    was = reg.enabled
    reg.enable()
    comm_metrics.configure(enabled=True)
    try:
        def body(x):
            g = quantized_all_gather(x, "fsdp")
            return quantized_reduce_scatter(g, "fsdp")

        fn = jax.shard_map(body, mesh=mesh8, in_specs=P("fsdp"),
                           out_specs=P("fsdp"), check_vma=False)
        jax.eval_shape(fn, jax.ShapeDtypeStruct((8, 512), jnp.float32))
        assert reg.get("ds_comm_q_all_gather_calls_total").value >= 1
        q_bytes = reg.get("ds_comm_q_all_gather_bytes_total",
                          labels={"dtype": "int8"})
        assert q_bytes is not None and q_bytes.value > 0
        assert reg.get("ds_comm_q_reduce_scatter_calls_total").value >= 1
    finally:
        comm_metrics.configure(enabled=False)
        comm_metrics.reset()
        reg.reset()
        if not was:
            reg.disable()


# ---------------------------------------------------------------------------
# acceptance smoke: ZeRO-3 training with telemetry on, scraped via /statz
# ---------------------------------------------------------------------------


def _tiny_lm_engine(mesh, telemetry: bool):
    from deepspeed_tpu.models import causal_lm

    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=32,
                      intermediate_size=64, num_heads=2, num_kv_heads=1,
                      vocab_size=128, remat=False)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3,
                                 "stage3_param_persistence_threshold": 0},
           "steps_per_print": 10**9}
    if telemetry:
        cfg["comms_logger"] = {"enabled": True}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, mesh=mesh, rng=jax.random.PRNGKey(3))
    return engine


def _run_steps(engine, steps=3, seq=16):
    tokens = np.asarray(
        jax.random.randint(jax.random.PRNGKey(0), (8, seq), 0, 128),
        dtype=np.int32)
    losses = []
    for _ in range(steps):
        losses.append(float(engine.forward((tokens, tokens))))
        engine.step()
    return losses


def test_zero3_training_smoke_exposes_comm_and_mfu_via_statz(mesh8):
    from deepspeed_tpu.monitor.server import MetricsServer

    reg = get_registry()
    was = reg.enabled
    reg.reset()
    engine = _tiny_lm_engine(mesh8, telemetry=True)
    assert reg.enabled, "comms_logger block must enable the registry"
    server = MetricsServer(reg, port=0).start()
    try:
        losses_on = _run_steps(engine)
        # the training step timeline rides the same master switch: each
        # boundary retained its micro spans + the analytic comm plan
        from deepspeed_tpu.monitor.request_trace import get_step_timeline

        tl = get_step_timeline()
        assert tl.enabled and tl.steps_total >= 3
        last = tl.steps()[-1]
        assert last["micros"] and last.get("comm_plan")
        assert any(e[0] == "all_gather" for e in last["comm_plan"])
        with urllib.request.urlopen(f"{server.url}/statz", timeout=5) as r:
            snap = json.load(r)["metrics"]
        # nonzero all_gather bytes + latency (ZeRO-3 gathers 2x/micro)
        byt = snap["ds_comm_all_gather_bytes_total"]
        total = sum(v for v in byt.values()) if isinstance(byt, dict) else byt
        assert total > 0
        assert snap["ds_comm_all_gather_calls_total"] > 0
        assert snap["ds_comm_all_gather_seconds"]["count"] >= 3
        assert snap["ds_comm_all_gather_seconds"]["sum"] > 0
        assert snap["ds_comm_reduce_scatter_bytes_total"]
        # MFU/TFLOPS gauges: set from the 2nd boundary on
        assert snap["ds_train_tflops"] > 0
        assert 0 < snap["ds_train_mfu"] < 10  # sanity, CPU "peak" is fake
        # ISSUE 7 step-numerics gauges: loss + grad norm at the boundary
        # (values the engine already computed for _report)
        assert snap["ds_train_loss"] == pytest.approx(losses_on[-1])
        assert snap["ds_train_grad_norm"] > 0
        # shard-group byte breakdown was recorded at init
        assert snap["ds_mem_param_shard_bytes"] > 0
        # the engine timers still bridge (PR 2 behavior intact)
        assert snap["ds_train_forward_seconds"]["count"] >= 3
    finally:
        server.stop()
        comm_metrics.configure(enabled=False)
        comm_metrics.reset()
        from deepspeed_tpu.monitor.request_trace import get_step_timeline

        get_step_timeline().disable()
        get_step_timeline().reset()
        reg.reset()
        if not was:
            reg.disable()

    # telemetry OFF: identical loss trajectory (token/loss-identical)
    engine_off = _tiny_lm_engine(mesh8, telemetry=False)
    losses_off = _run_steps(engine_off)
    assert losses_on == pytest.approx(losses_off, rel=1e-6, abs=1e-7)
    assert reg.get("ds_train_tflops") is None or \
        reg.get("ds_train_tflops").value == 0


def test_metrics_dump_comms_table(tmp_path):
    """tools/metrics_dump.py --comms renders the per-collective summary."""
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    cm.commit([("all_gather", 4, 1 << 20, "float32", 8)], seconds=0.5)
    reg.gauge("ds_mem_peak_bytes").set(3 * (1 << 30))
    snap = tmp_path / "statz.json"
    snap.write_text(reg.statz_json())
    metrics = metrics_dump.load_snapshot(str(snap))
    table = metrics_dump.render_comms(metrics_dump.comms_rows(metrics))
    assert "all_gather" in table and "4" in table
    assert "1.00 MiB" in table and "GB/s" in table
    # ds_mem_* byte gauges humanize in the main table
    main_table = metrics_dump.render(metrics_dump.rows_from_snapshot(metrics))
    assert "3.00 GiB" in main_table


def test_metrics_dump_comms_compression_column(tmp_path):
    """The quantized transports' per-op compression column (quantized
    wire bytes vs the dense-twin series, both from ONE trace —
    comm/collectives_q.py): rendered as `<ratio>x`, blank for dense
    ops."""
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    # a quantized op: wire = int8 codes + fp32 scales, dense twin = fp32
    cm.commit([("q_all_reduce", 2, 1_000_000, "int8", 8, 3_500_000)],
              seconds=0.1)
    # a dense op on the same snapshot: no compression column
    cm.commit([("all_reduce", 2, 4_000_000, "float32", 8)], seconds=0.1)
    snap = tmp_path / "statz.json"
    snap.write_text(reg.statz_json())
    metrics = metrics_dump.load_snapshot(str(snap))
    rows = metrics_dump.comms_rows(metrics)
    by_op = {r[0]: r for r in rows}
    assert by_op["q_all_reduce"][3] == "3.50x"
    assert by_op["all_reduce"][3] == ""
    table = metrics_dump.render_comms(rows)
    assert "compress" in table and "3.50x" in table
