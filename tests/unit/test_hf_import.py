"""HF checkpoint import tests (VERDICT r2 item 9): fixture-based logits
parity against tiny HF-format checkpoints (GPT-2, Llama, Mixtral) written by
the transformers library itself.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.module_inject import causal_lm_from_hf, is_hf_checkpoint

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _save_tiny(tmp_path, kind: str) -> str:
    torch.manual_seed(0)
    out = str(tmp_path / kind)
    if kind == "gpt2":
        cfg = transformers.GPT2Config(
            vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
        model = transformers.GPT2LMHeadModel(cfg)
    elif kind == "llama":
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, tie_word_embeddings=False)
        model = transformers.LlamaForCausalLM(cfg)
    elif kind == "opt":
        cfg = transformers.OPTConfig(
            vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            word_embed_proj_dim=32, dropout=0.0, do_layer_norm_before=True)
        model = transformers.OPTForCausalLM(cfg)
    elif kind == "qwen2":
        cfg = transformers.Qwen2Config(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, tie_word_embeddings=False)
        model = transformers.Qwen2ForCausalLM(cfg)
    elif kind == "gpt_neox":
        cfg = transformers.GPTNeoXConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, rotary_pct=0.25,
            use_parallel_residual=True, tie_word_embeddings=False,
            hidden_dropout=0.0, attention_dropout=0.0)
        model = transformers.GPTNeoXForCausalLM(cfg)
    elif kind == "bloom":
        cfg = transformers.BloomConfig(
            vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
            hidden_dropout=0.0, attention_dropout=0.0)
        model = transformers.BloomForCausalLM(cfg)
    elif kind == "gptj":
        cfg = transformers.GPTJConfig(
            vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
            rotary_dim=4, n_inner=64, resid_pdrop=0.0, embd_pdrop=0.0,
            attn_pdrop=0.0, tie_word_embeddings=False)
        model = transformers.GPTJForCausalLM(cfg)
    else:
        cfg = transformers.MixtralConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            max_position_embeddings=64, tie_word_embeddings=False)
        model = transformers.MixtralForCausalLM(cfg)
    model.eval()
    # HF _init_weights zeroes every Linear bias, which would make the
    # bias-plumbing paths (gpt-j mlp/lm-head bias, qkv bias, bloom biases)
    # vacuously "pass" even if a mapped bias were dropped — perturb them
    with torch.no_grad():
        for name, p in model.named_parameters():
            if name.endswith(".bias") and p.abs().sum() == 0:
                p.add_(torch.randn_like(p) * 0.05)
    model.save_pretrained(out, safe_serialization=True)
    return out


def _hf_logits(path: str, toks: np.ndarray) -> np.ndarray:
    model = transformers.AutoModelForCausalLM.from_pretrained(path)
    model.eval()
    with torch.no_grad():
        return model(torch.tensor(toks)).logits.numpy()


@pytest.mark.parametrize("kind", ["gpt2", "llama", "opt", "qwen2",
                                  "gpt_neox", "bloom", "gptj"])
def test_logits_parity(tmp_path, kind, mesh8):
    path = _save_tiny(tmp_path, kind)
    assert is_hf_checkpoint(path)
    toks = np.array([[1, 5, 9, 2, 77, 31, 8, 4]], np.int32)
    want = _hf_logits(path, toks)

    model, params = causal_lm_from_hf(path, mesh=mesh8)
    model.config.remat = False
    got = np.asarray(jax.jit(model.apply)(params, jnp.asarray(toks)))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_mixtral_imports_and_runs(tmp_path, mesh8):
    """Mixtral: exact logits parity is confounded by our fixed-capacity
    GShard dispatch (HF routes densely per token), so assert import shape
    correctness + a finite forward instead."""
    path = _save_tiny(tmp_path, "mixtral")
    model, params = causal_lm_from_hf(path, mesh=mesh8)
    model.config.remat = False
    assert params["layers"]["mlp"]["w_up"].shape == (2, 4, 32, 64)
    assert params["layers"]["mlp"]["gate_w"].shape == (2, 32, 4)
    toks = jnp.asarray(np.array([[1, 5, 9, 2]], np.int32))
    logits = jax.jit(model.apply)(params, toks)
    assert np.isfinite(np.asarray(logits)).all()
    assert logits.shape == (1, 4, 128)


def test_inference_engine_loads_hf(tmp_path, mesh8):
    import deepspeed_tpu

    path = _save_tiny(tmp_path, "llama")
    model, params = causal_lm_from_hf(path, mesh=mesh8)
    model.config.remat = False
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 32,
                       "checkpoint": path})
    out = engine.generate(jnp.asarray([[1, 5, 9]]), max_new_tokens=4)
    assert out.shape == (1, 7)


@pytest.mark.parametrize("kind", ["gpt_neox", "qwen2", "opt", "bloom",
                                  "gptj"])
def test_generate_parity(tmp_path, kind, mesh8):
    """The DECODE path re-implements the layer math (decoding.py), so the
    parallel-residual + partial-rope + bias branches need their own parity
    evidence: greedy generation must match HF token for token."""
    import deepspeed_tpu

    path = _save_tiny(tmp_path, kind)
    toks = np.array([[1, 5, 9, 2]], np.int32)
    model_hf = transformers.AutoModelForCausalLM.from_pretrained(path)
    model_hf.eval()
    # disable HF's eos early-stop (random tiny weights may pick eos first,
    # which would shrink the compared span to one token); min_new_tokens is
    # NOT equivalent — it bans eos and changes the greedy argmax
    model_hf.generation_config.eos_token_id = None
    with torch.no_grad():
        # explicit full mask: generate() otherwise auto-masks prompt tokens
        # equal to pad_token_id (OPT's pad is id 1, which the prompt holds),
        # silently diverging from the plain-forward semantics we compare to
        want = model_hf.generate(torch.tensor(toks),
                                 attention_mask=torch.ones(toks.shape,
                                                           dtype=torch.long),
                                 max_new_tokens=6, do_sample=False).numpy()
    model, params = causal_lm_from_hf(path, mesh=mesh8)
    model.config.remat = False
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    engine.set_params(params)
    got = np.asarray(engine.generate(jnp.asarray(toks), max_new_tokens=6,
                                     do_sample=False))
    assert want.shape[1] == toks.shape[1] + 6, want.shape  # full span compared
    np.testing.assert_array_equal(got, want)
