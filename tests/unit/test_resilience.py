"""Preemption-safe training: crash-atomic checkpoints, verified load with
walk-back, retention GC, SIGTERM emergency saves, and the offline
verifier (docs/RESILIENCE.md).

The acceptance bar: a kill at ANY point during ``save_checkpoint`` never
leaves ``latest`` pointing at a checkpoint that fails to load, and every
corruption the manifest can express (torn tail, bit flip, missing files,
missing tag) makes the loader walk back to the newest valid tag instead
of crashing.  Faults come from the injection harness
(``deepspeed_tpu/testing/chaos.py``)."""

import glob
import json
import os
import shutil
import signal
import sys

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.runtime.checkpoint_engine import atomic
from deepspeed_tpu.testing import chaos
from tests.unit.simple_model import SimpleModel, random_dataset

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _make_engine(stage=0, ckpt_cfg=None):
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": stage},
           "steps_per_print": 10**9}
    if ckpt_cfg:
        cfg["checkpoint"] = ckpt_cfg
    x, y = random_dataset(n=16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg,
        rng=jax.random.PRNGKey(3))
    return engine, (x[:8], y[:8])


def _train_steps(engine, batch, n=1):
    loss = None
    for _ in range(n):
        loss = engine.forward(batch)
        engine.step()
    return loss


def _params_snapshot(engine):
    # OWNED copies: on CPU, device_get can return views aliasing device
    # buffers that the next (donating) train step mutates in place
    return jax.tree.map(lambda x: np.array(x),
                        jax.device_get(engine.state.params))


def _assert_params_equal(engine, snap):
    for a, b in zip(jax.tree.leaves(snap),
                    jax.tree.leaves(jax.device_get(engine.state.params))):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


# ---------------------------------------------------------------------------
# atomic layout unit tests (no engine)
# ---------------------------------------------------------------------------


def _toy_ckpt(tmp_path, tag="t", payload=b"a" * 4096):
    d = tmp_path / tag
    (d / "model_states").mkdir(parents=True)
    (d / "model_states" / "shard_p0.bin").write_bytes(payload)
    (d / "client_state.json").write_text(json.dumps({"client_state": {}}))
    atomic.write_manifest(str(d), tag, extra={"world_size": 1,
                                              "zero_stage": 0})
    return str(d)


def test_manifest_write_and_verify(tmp_path):
    d = _toy_ckpt(tmp_path)
    st = atomic.verify_dir(d)
    assert st.ok and st.state == "valid"
    m = st.manifest
    assert m["format_version"] == atomic.FORMAT_VERSION
    assert m["world_size"] == 1 and m["zero_stage"] == 0
    # every file except the manifest itself is covered, with size + sha256
    assert set(m["files"]) == {"model_states/shard_p0.bin",
                               "client_state.json"}
    for meta in m["files"].values():
        assert meta["nbytes"] > 0 and len(meta["sha256"]) == 64


def test_verify_catches_truncation_size_only(tmp_path):
    d = _toy_ckpt(tmp_path)
    chaos.truncate_file(os.path.join(d, "model_states", "shard_p0.bin"), 7)
    st = atomic.verify_dir(d, level="fast")      # no checksums needed
    assert st.state == "corrupt"
    assert any("size mismatch" in p for p in st.problems)


def test_verify_catches_bit_flip_full_only(tmp_path):
    d = _toy_ckpt(tmp_path)
    chaos.flip_bit(os.path.join(d, "model_states", "shard_p0.bin"))
    assert atomic.verify_dir(d, level="fast").ok      # size unchanged
    st = atomic.verify_dir(d, level="full")
    assert st.state == "corrupt"
    assert any("checksum mismatch" in p for p in st.problems)


def test_verify_catches_missing_file_and_dir(tmp_path):
    d = _toy_ckpt(tmp_path)
    os.remove(os.path.join(d, "client_state.json"))
    st = atomic.verify_dir(d)
    assert st.state == "corrupt"
    assert any("missing file" in p for p in st.problems)
    assert atomic.verify_dir(str(tmp_path / "nope")).state == "missing"
    shutil.rmtree(os.path.join(d))
    assert atomic.verify_dir(d).state == "missing"


def test_list_tags_excludes_stage_and_orders_newest_first(tmp_path):
    _toy_ckpt(tmp_path, "older")
    _toy_ckpt(tmp_path, "newer")
    os.makedirs(tmp_path / (atomic.TMP_PREFIX + "staged"))
    (tmp_path / "latest").write_text("newer")       # plain file: not a tag
    assert atomic.list_tags(str(tmp_path)) == ["newer", "older"]


def test_latest_pointer_roundtrip(tmp_path):
    assert atomic.read_latest(str(tmp_path)) is None
    atomic.write_latest(str(tmp_path), "global_step7")
    assert atomic.read_latest(str(tmp_path)) == "global_step7"
    # atomic replace: no .tmp debris left behind
    assert [n for n in os.listdir(tmp_path) if n.startswith("latest")] == \
        ["latest"]


# ---------------------------------------------------------------------------
# chaos-primitive contracts
# ---------------------------------------------------------------------------


def test_crash_on_write_cuts_at_exact_offset(tmp_path):
    target = str(tmp_path / "f.bin")
    with pytest.raises(chaos.InjectedFault):
        with chaos.crash_on_write(10, str(tmp_path)):
            with open(target, "wb") as fh:
                fh.write(b"x" * 6)       # under budget
                fh.write(b"y" * 6)       # crosses it: 4 more land, then die
    assert os.path.getsize(target) == 10      # the partial prefix IS on disk
    # unmatched paths are untouched
    with chaos.crash_on_write(0, str(tmp_path / "only")):
        (tmp_path / "other.txt").write_text("fine")


def test_fail_after_calls(tmp_path):
    class Thing:
        def hit(self):
            return "ok"

    t = Thing()
    with chaos.fail_after_calls(t, "hit", 2) as state:
        assert t.hit() == "ok" and t.hit() == "ok"
        with pytest.raises(chaos.InjectedFault):
            t.hit()
        assert state["calls"] == 3
    assert t.hit() == "ok"               # restored


# ---------------------------------------------------------------------------
# crash-atomic engine saves: kill anywhere, `latest` still loads
# ---------------------------------------------------------------------------


def test_kill_at_any_byte_offset_mid_save_never_corrupts_latest(tmp_path):
    """The acceptance sweep: inject a crash at byte offsets spanning the
    whole save (first write → almost-done) and prove ``latest`` still
    names a tag that verifies AND loads after every single one."""
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    p1 = _params_snapshot(engine)
    total = sum(os.path.getsize(os.path.join(root, f))
                for root, _d, files in os.walk(os.path.join(save_dir, "t1"))
                for f in files)
    assert total > 1000
    _train_steps(engine, batch)          # diverge from t1

    offsets = [0, 1, 333, total // 2, total - 100]
    for i, off in enumerate(offsets):
        tag = f"crash{i}"
        with pytest.raises(chaos.InjectedFault):
            with chaos.crash_on_write(off, save_dir):
                engine.save_checkpoint(save_dir, tag=tag)
        # the pointer never moved, the dead tag never published
        assert atomic.read_latest(save_dir) == "t1"
        assert not os.path.exists(os.path.join(save_dir, tag))
        assert atomic.list_tags(save_dir) == ["t1"]
        st = atomic.verify_dir(os.path.join(save_dir, "t1"), level="full")
        assert st.ok, (off, st.problems)

    # ...and the surviving checkpoint actually LOADS (not just verifies)
    ckpt_dir, _ = engine.load_checkpoint(save_dir)
    assert ckpt_dir.endswith("t1")
    _assert_params_equal(engine, p1)

    # a later clean save publishes normally over the crash debris
    ckpt = engine.save_checkpoint(save_dir, tag="t2")
    assert atomic.read_latest(save_dir) == "t2"
    assert atomic.verify_dir(ckpt, level="full").ok


def test_regression_latest_is_written_only_after_commit(tmp_path):
    """The pinned ordering bug: `latest` used to be written (plain
    open/write) BEFORE ``checkpoint_engine.commit`` — a crash between the
    two barriers published a partial checkpoint.  Kill exactly there and
    assert the pointer never moved, even though every shard is already on
    disk."""
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    _train_steps(engine, batch)
    with pytest.raises(chaos.InjectedFault):
        with chaos.crash_before(engine.checkpoint_engine, "commit"):
            engine.save_checkpoint(save_dir, tag="t2")
    assert atomic.read_latest(save_dir) == "t1"
    assert not os.path.exists(os.path.join(save_dir, "t2"))
    # everything was staged (the crash hit between write and commit),
    # proving the kill window is exactly the old bug's
    stage = atomic.stage_path(save_dir, "t2")
    assert os.path.isdir(stage)
    assert os.path.exists(os.path.join(stage, atomic.MANIFEST_NAME))
    # the stale stage is debris, not a tag; the next save clears it
    assert atomic.list_tags(save_dir) == ["t1"]
    engine.save_checkpoint(save_dir, tag="t2")
    assert not os.path.isdir(stage)
    assert atomic.read_latest(save_dir) == "t2"


def _make_pr10_engine(int8=False, streamed=False):
    """Engines producing the PR 10 checkpoint formats the original
    kill-at-byte sweep predates: host-offloaded masters (``offload_states``
    dir; ``int8_masters`` requantizes on save) and the streamed
    offload_param path (pinned param refresh at save time)."""
    from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh()
    set_global_mesh(mesh)
    zero = {"stage": 3,
            "offload_optimizer": {"device": "cpu", "int8_masters": int8,
                                  "quant_block": 64}}
    if streamed:
        zero["offload_param"] = {"device": "cpu"}
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=32,
                      intermediate_size=64, num_heads=2, num_kv_heads=2,
                      vocab_size=128, max_seq_len=32, remat=False)
    cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1, "bf16": {"enabled": True},
           "zero_optimization": zero,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, mesh=mesh, rng=jax.random.PRNGKey(5))
    toks = np.asarray(jax.random.randint(jax.random.PRNGKey(7), (8, 16),
                                         0, 128))
    return engine, (toks, toks)


@pytest.mark.parametrize("fmt", ["int8_masters", "streamed_int8"])
def test_kill_at_byte_offset_pr10_formats_never_corrupt_latest(tmp_path,
                                                               fmt):
    """The PR 8 kill-at-arbitrary-byte acceptance, re-run against the
    checkpoint formats PR 10 added after it was written: int8 host
    masters (requant-on-save ``offload_states``) and the streamed
    offload-param path.  A crash at any byte offset — including inside
    ``offload_states`` — must leave ``latest`` naming a tag that verifies
    AND loads with the exact pre-crash params + master state."""
    engine, batch = _make_pr10_engine(int8=True,
                                      streamed=fmt == "streamed_int8")
    _train_steps(engine, batch)
    if fmt == "streamed_int8":
        assert engine._streamed is not None     # the format under test
    assert engine._offload_opt.int8_masters
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    assert os.path.isdir(os.path.join(save_dir, "t1", "offload_states"))
    p1 = _params_snapshot(engine)
    m1 = [m.copy() for m in engine._offload_opt.masters()]
    _train_steps(engine, batch)              # diverge from t1

    total = sum(os.path.getsize(os.path.join(root, f))
                for root, _d, files in os.walk(os.path.join(save_dir, "t1"))
                for f in files)
    # offsets spanning the save, plus one aimed INSIDE offload_states
    off_dir_start = sum(
        os.path.getsize(os.path.join(root, f))
        for root, _d, files in os.walk(os.path.join(save_dir, "t1",
                                                    "model_states"))
        for f in files)
    offsets = [0, total // 2, off_dir_start + 100, total - 50]
    for i, off in enumerate(offsets):
        with pytest.raises(chaos.InjectedFault):
            with chaos.crash_on_write(off, save_dir):
                engine.save_checkpoint(save_dir, tag=f"crash{i}")
        assert atomic.read_latest(save_dir) == "t1"
        assert atomic.list_tags(save_dir) == ["t1"]
        st = atomic.verify_dir(os.path.join(save_dir, "t1"), level="full")
        assert st.ok, (off, st.problems)
        assert atomic.deep_verify(os.path.join(save_dir, "t1")) == []

    ckpt_dir, _ = engine.load_checkpoint(save_dir)
    assert ckpt_dir.endswith("t1")
    _assert_params_equal(engine, p1)
    # the int8 store requantized back to exactly the saved masters
    for a, b in zip(m1, engine._offload_opt.masters()):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    # a later clean save publishes over the debris and keeps training
    engine.save_checkpoint(save_dir, tag="t2")
    assert atomic.read_latest(save_dir) == "t2"
    loss = _train_steps(engine, batch)
    assert np.isfinite(float(loss))


def test_corrupt_offload_states_falls_back(tmp_path):
    """A bit flip inside the offload_states master file is caught by the
    manifest (it covers EVERY file in the tag, not just shards) and the
    loader walks back."""
    engine, batch = _make_pr10_engine(int8=True)
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    p1 = _params_snapshot(engine)
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir, tag="t2")
    leaf = glob.glob(os.path.join(save_dir, "t2", "offload_states",
                                  "leaf*.npy"))[0]
    chaos.flip_bit(leaf)
    ckpt_dir, _ = engine.load_checkpoint(save_dir)     # latest -> t2
    assert ckpt_dir is not None and ckpt_dir.endswith("t1")
    _assert_params_equal(engine, p1)


# ---------------------------------------------------------------------------
# verified load: corrupt/truncated/missing tag -> walk back to newest valid
# ---------------------------------------------------------------------------


def _corruption_fallback_case(tmp_path, corrupt):
    """Save t1, t2; corrupt t2 via ``corrupt(t2_dir)``; load must fall
    back to t1 and account for it."""
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    p1 = _params_snapshot(engine)
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir, tag="t2")
    corrupt(os.path.join(save_dir, "t2"))

    reg = get_registry()
    reg.enable()
    fails0 = reg.counter("ds_ckpt_verify_failures_total").value
    fb0 = reg.counter("ds_ckpt_fallbacks_total").value
    flight = get_flight_recorder()
    flight.reset()
    flight.enable()
    try:
        ckpt_dir, _ = engine.load_checkpoint(save_dir)   # latest -> t2
        assert ckpt_dir is not None and ckpt_dir.endswith("t1")
        _assert_params_equal(engine, p1)
        assert reg.counter("ds_ckpt_verify_failures_total").value - fails0 >= 1
        assert reg.counter("ds_ckpt_fallbacks_total").value - fb0 == 1
        kinds = [e["kind"] for e in flight.events()]
        assert "ckpt_verify_fail" in kinds
        assert "ckpt_fallback" in kinds
        fb = [e for e in flight.events() if e["kind"] == "ckpt_fallback"][-1]
        assert fb["requested"] == "t2" and fb["loaded"] == "t1"
    finally:
        flight.disable()
        reg.disable()


def test_bit_flipped_model_states_falls_back(tmp_path):
    def corrupt(d):
        shard = glob.glob(os.path.join(d, "model_states", "shard_p*.bin"))[0]
        chaos.flip_bit(shard)

    _corruption_fallback_case(tmp_path, corrupt)


def test_truncated_optim_states_falls_back(tmp_path):
    def corrupt(d):
        shard = glob.glob(os.path.join(d, "optim_states", "shard_p*.bin"))[0]
        chaos.truncate_file(shard, drop_bytes=64)

    _corruption_fallback_case(tmp_path, corrupt)


def test_missing_tag_dir_falls_back(tmp_path):
    _corruption_fallback_case(tmp_path, shutil.rmtree)


def test_lost_latest_pointer_still_resumes_newest_valid(tmp_path):
    """latest itself vanishing (partial dir loss) walks back through
    list_tags instead of giving up."""
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir, tag="t2")
    p2 = _params_snapshot(engine)
    os.remove(os.path.join(save_dir, "latest"))
    _train_steps(engine, batch)          # diverge in memory
    ckpt_dir, _ = engine.load_checkpoint(save_dir)
    assert ckpt_dir.endswith("t2")       # newest valid by manifest time
    _assert_params_equal(engine, p2)


def test_nothing_loadable_returns_none(tmp_path):
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    assert engine.load_checkpoint(str(tmp_path)) == (None, {})
    # a save dir where every tag is corrupt also degrades to (None, {})
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    shard = glob.glob(os.path.join(save_dir, "t1", "model_states",
                                   "shard_p*.bin"))[0]
    chaos.flip_bit(shard)
    assert engine.load_checkpoint(save_dir) == (None, {})


# ---------------------------------------------------------------------------
# retention GC
# ---------------------------------------------------------------------------


def test_retention_gc_keeps_last_n_and_latest(tmp_path):
    engine, batch = _make_engine(ckpt_cfg={"keep_last_n": 2})
    save_dir = str(tmp_path)
    reg = get_registry()
    reg.enable()
    try:
        for i in range(1, 5):
            _train_steps(engine, batch)
            engine.save_checkpoint(save_dir, tag=f"t{i}")
        assert atomic.list_tags(save_dir) == ["t4", "t3"]
        assert atomic.read_latest(save_dir) == "t4"
        assert reg.gauge("ds_ckpt_retained").value == 2
        # the survivors still load
        ckpt_dir, _ = engine.load_checkpoint(save_dir)
        assert ckpt_dir.endswith("t4")
    finally:
        reg.disable()


def test_retention_gc_never_deletes_latest_even_if_old(tmp_path):
    """latest pinned to an OLD tag (operator rollback): GC must keep it
    alive alongside the newest keep_last_n."""
    engine, batch = _make_engine(ckpt_cfg={"keep_last_n": 1})
    save_dir = str(tmp_path)
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir, tag="pinned")
    atomic.write_latest(save_dir, "pinned")
    for i in range(2):
        _train_steps(engine, batch)
        engine.save_checkpoint(save_dir, tag=f"n{i}", save_latest=False)
    tags = atomic.list_tags(save_dir)
    assert "pinned" in tags              # latest survived the budget
    assert "n1" in tags                  # newest valid kept
    assert "n0" not in tags              # oldest beyond budget collected


# ---------------------------------------------------------------------------
# SIGTERM -> emergency save at the next optimizer boundary
# ---------------------------------------------------------------------------


def test_sigterm_emergency_save_at_boundary(tmp_path):
    engine, batch = _make_engine()
    save_dir = str(tmp_path)
    handler = engine.enable_preemption_save(
        save_dir, client_state_fn=lambda: {"data_step": 41},
        exit_after=False)
    flight = get_flight_recorder()
    flight.reset()
    flight.enable()
    reg = get_registry()
    reg.enable()
    em0 = reg.counter("ds_ckpt_emergency_saves_total").value
    try:
        _train_steps(engine, batch)              # no signal: no save
        assert atomic.read_latest(save_dir) is None
        os.kill(os.getpid(), signal.SIGTERM)     # the grace-window signal
        assert handler.requested
        _train_steps(engine, batch)              # boundary takes the save
        tag = atomic.read_latest(save_dir)
        assert tag == "global_step2"
        st = atomic.verify_dir(os.path.join(save_dir, tag), level="full")
        assert st.ok
        # dataloader position rode along for a step-accurate resume
        _, client_state = engine.load_checkpoint(save_dir)
        assert client_state == {"data_step": 41}
        assert not handler.requested             # latched once, cleared
        kinds = [e["kind"] for e in flight.events()]
        assert "ckpt_emergency" in kinds
        assert reg.counter("ds_ckpt_emergency_saves_total").value - em0 == 1

        # exit_after=True: the boundary exits with the preempted code for
        # the supervisor (programmatic request — same latch the signal
        # sets)
        engine.enable_preemption_save(save_dir, exit_after=True)
        handler.request()
        with pytest.raises(SystemExit) as ei:
            _train_steps(engine, batch)
        from deepspeed_tpu.runtime.preemption import PREEMPTED_EXIT_CODE

        assert ei.value.code == PREEMPTED_EXIT_CODE
        assert atomic.read_latest(save_dir) == "global_step3"
    finally:
        handler.uninstall()
        flight.disable()
        reg.disable()


def test_failed_emergency_save_keeps_the_latch(tmp_path):
    """A transient failure of the emergency save must not DROP the
    preemption request: the latch clears only after a successful save, so
    the next boundary retries instead of running to the SIGKILL deadline
    with no checkpoint."""
    engine, batch = _make_engine()
    save_dir = str(tmp_path)
    handler = engine.enable_preemption_save(save_dir, exit_after=False)
    try:
        handler.request()
        with chaos.crash_before(engine.checkpoint_engine, "save"):
            with pytest.raises(chaos.InjectedFault):
                _train_steps(engine, batch)
        assert handler.requested, "failed save dropped the latch"
        _train_steps(engine, batch)          # next boundary retries
        assert not handler.requested
        tag = atomic.read_latest(save_dir)
        assert tag is not None
        assert atomic.verify_dir(os.path.join(save_dir, tag),
                                 level="full").ok
    finally:
        handler.uninstall()


def test_resave_same_tag_overwrites_cleanly(tmp_path):
    """Re-saving an existing tag (emergency save colliding with a regular
    one) replaces it whole and leaves no ``.trash.`` debris behind."""
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    _train_steps(engine, batch)
    p2 = _params_snapshot(engine)
    engine.save_checkpoint(save_dir, tag="t1")
    assert atomic.verify_dir(os.path.join(save_dir, "t1"),
                             level="full").ok
    assert [n for n in os.listdir(save_dir)
            if n.startswith(atomic.TRASH_PREFIX)] == []
    _train_steps(engine, batch)              # diverge, then load back
    engine.load_checkpoint(save_dir, tag="t1")
    _assert_params_equal(engine, p2)


def test_crashed_publish_trash_is_reported_and_swept(tmp_path):
    """A publish killed between rename-aside and cleanup leaks a
    checkpoint-sized ``.trash.`` dir: the offline auditor reports it and
    the next save's GC sweeps it."""
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    leak = os.path.join(save_dir, ".trash.t0.12345")
    os.makedirs(os.path.join(leak, "model_states"))
    ckpt_verify = _tool("ckpt_verify")
    rep = ckpt_verify.audit(save_dir)
    assert [d["name"] for d in rep["stage_debris"]] == [".trash.t0.12345"]
    assert atomic.list_tags(save_dir) == ["t1"]   # never mistaken for a tag
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir, tag="t2")
    assert not os.path.exists(leak)


def test_preempt_exit_code_contract_matches_supervisor():
    """runtime/preemption.py and the no-jax tools/train_supervisor.py
    carry the same exit-code default (both read DS_PREEMPT_EXIT_CODE) —
    drift here would turn clean preemptions into counted crashes."""
    from deepspeed_tpu.runtime.preemption import PREEMPTED_EXIT_CODE

    sup = _tool("train_supervisor")
    assert sup.PREEMPT_EXIT_CODE == PREEMPTED_EXIT_CODE


# ---------------------------------------------------------------------------
# exception mid-step (the third chaos fault) still leaves a loadable chain
# ---------------------------------------------------------------------------


def test_exception_mid_step_then_resume(tmp_path):
    engine, batch = _make_engine()
    save_dir = str(tmp_path)
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir)
    p1 = _params_snapshot(engine)
    with chaos.fail_after_calls(engine, "_apply_fn", 0):
        with pytest.raises(chaos.InjectedFault):
            _train_steps(engine, batch)
    # the crash did not touch the checkpoint chain: reload and continue
    ckpt_dir, _ = engine.load_checkpoint(save_dir)
    assert ckpt_dir is not None
    _assert_params_equal(engine, p1)
    loss = _train_steps(engine, batch)
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# offline verifier (tools/ckpt_verify.py)
# ---------------------------------------------------------------------------


def test_ckpt_verify_selftest():
    """tools/ckpt_verify.py --selftest builds a synthetic save dir through
    the real atomic module and asserts the audit verdicts."""
    ckpt_verify = _tool("ckpt_verify")
    assert ckpt_verify.main(["ckpt_verify", "--selftest"]) == 0


def test_ckpt_verify_audits_real_engine_checkpoints(tmp_path, capsys):
    engine, batch = _make_engine()
    _train_steps(engine, batch)
    save_dir = str(tmp_path)
    engine.save_checkpoint(save_dir, tag="t1")
    _train_steps(engine, batch)
    engine.save_checkpoint(save_dir, tag="t2")
    ckpt_verify = _tool("ckpt_verify")
    rep = ckpt_verify.audit(save_dir)
    assert rep["latest"] == "t2" and rep["loadable"] == "t2"
    assert {e["tag"]: e["state"] for e in rep["tags"]} == \
        {"t1": "valid", "t2": "valid"}
    assert all(e["world_size"] == jax.device_count()
               and e["zero_stage"] == 0 for e in rep["tags"])
    # corrupt latest: the CLI reports the walk-back target and exits 0
    shard = glob.glob(os.path.join(save_dir, "t2", "model_states",
                                   "shard_p*.bin"))[0]
    chaos.flip_bit(shard)
    assert ckpt_verify.main(["ckpt_verify", save_dir]) == 0
    out = capsys.readouterr().out
    assert "walk-back" in out and "corrupt" in out
    # nothing valid left: nonzero exit
    shard1 = glob.glob(os.path.join(save_dir, "t1", "model_states",
                                    "shard_p*.bin"))[0]
    chaos.flip_bit(shard1)
    assert ckpt_verify.main(["ckpt_verify", save_dir]) == 1
