"""Offload streaming layer (ISSUE 11 / ROADMAP item 3): blockwise int8
codec, ParamStreamer staging/prefetch, int8 host masters, and the relay
metrics ledger.

The contracts pinned here:
- prefetch on/off is loss-IDENTICAL (transport order never changes math);
- int8 masters / int8 stream train to loss PARITY with fp32 masters
  within an rtol bound (the codec is lossy by design; the bound is the
  contract), and the H2D relay ships measurably fewer bytes;
- the persistent staging ring actually recycles its buffers (pointer
  cycling under jit-only consumption);
- ``ds_offload_*`` series populate on both the streamed and the
  optimizer-boundary relay.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.comm.quant import (dequantize_blockwise,
                                      dequantize_blockwise_np,
                                      dequantize_tree_np,
                                      quantize_blockwise,
                                      quantize_blockwise_np,
                                      quantize_tree_np)
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.metrics import get_registry


# ---------------------------------------------------------------------------
# comm/quant.py codec units
# ---------------------------------------------------------------------------

def test_quant_roundtrip_error_bound(rng):
    x = np.asarray(jax.random.normal(rng, (1000,))) * 3.0
    q, s = quantize_blockwise_np(x, block=128)
    assert q.dtype == np.int8 and q.shape == (8, 128)
    back = dequantize_blockwise_np(q, s, x.size)
    # absmax scaling: error <= scale/2 = blockwise absmax / 254
    for b in range(8):
        bound = np.abs(x[b * 128:(b + 1) * 128]).max() / 254 + 1e-7
        assert np.abs(back[b * 128:(b + 1) * 128]
                      - x[b * 128:(b + 1) * 128]).max() <= bound
    # exact zeros stay exact; an all-zero block has scale 0
    zq, zs = quantize_blockwise_np(np.zeros(300), block=128)
    assert (dequantize_blockwise_np(zq, zs, 300) == 0).all()
    # requantizing a dequantized block is (near-)lossless
    q2, s2 = quantize_blockwise_np(back, block=128)
    back2 = dequantize_blockwise_np(q2, s2, x.size)
    np.testing.assert_allclose(back2, back, rtol=1e-6, atol=1e-7)


def test_quant_np_and_jnp_twins_agree(rng):
    x = np.asarray(jax.random.normal(rng, (7, 33)), np.float32)
    qn, sn = quantize_blockwise_np(x, block=64)
    qj, sj = jax.jit(lambda a: quantize_blockwise(a, block=64))(x)
    np.testing.assert_array_equal(qn, np.asarray(qj))
    np.testing.assert_allclose(sn, np.asarray(sj), rtol=1e-6)
    back_j = jax.jit(lambda q, s: dequantize_blockwise(q, s, x.shape))(qn, sn)
    np.testing.assert_allclose(dequantize_blockwise_np(
        qn, sn, x.size).reshape(x.shape), np.asarray(back_j), rtol=1e-6)


def test_quant_sqrt_space_nonnegative(rng):
    v = np.abs(np.asarray(jax.random.normal(rng, (500,)))) ** 2
    q, s = quantize_blockwise_np(v, block=128, sqrt_space=True)
    back = dequantize_blockwise_np(q, s, v.size, sqrt_space=True)
    assert (back >= 0).all()
    # sqrt-space code: relative error on the sqrt is bounded, so large
    # values come back tight
    big = v > 0.1 * v.max()
    np.testing.assert_allclose(back[big], v[big], rtol=3e-2)


def test_quant_tree_roundtrip(rng):
    tree = {"a": np.asarray(jax.random.normal(rng, (3, 5)), np.float32),
            "b": {"c": np.ones((130,), np.float32)}}
    qt = quantize_tree_np(tree, block=64)
    assert qt.nbytes < sum(a.nbytes for a in jax.tree.leaves(tree))
    back = dequantize_tree_np(qt)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, atol=np.abs(a).max() / 120)


# ---------------------------------------------------------------------------
# ParamStreamer transport
# ---------------------------------------------------------------------------

def _streamer(**kw):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.runtime.zero.streaming import ParamStreamer

    mesh = build_mesh(devices=jax.devices()[:1])
    sh = {"w": NamedSharding(mesh, P()), "b": NamedSharding(mesh, P())}
    np_layers = {"w": np.arange(6 * 4 * 8, dtype=np.float32
                                ).reshape(6, 4, 8),
                 "b": np.ones((6, 8), np.float32)}
    s = ParamStreamer(sh, **kw)
    s.refresh(np_layers)
    return s, np_layers


def test_staging_ring_recycles_buffers():
    """The persistent staging ring: consumed payloads cycle over exactly
    ``staging_slots`` device buffers (jit-only consumption — a numpy view
    would pin the buffer externally and legitimately break reuse)."""
    s, np_layers = _streamer(staging_slots=2)
    read = jax.jit(lambda t: t["w"].sum() + t["b"].sum())
    ptrs, sums = [], []
    for i in range(6):
        s.prefetch(i)
        lp = s.take(i)
        sums.append(float(read(lp)))
        ptrs.append(lp["w"].unsafe_buffer_pointer())
        del lp
    want = [float(np_layers["w"][i].sum() + np_layers["b"][i].sum())
            for i in range(6)]
    assert sums == pytest.approx(want)
    assert len(set(ptrs)) == 2, f"staging not recycled: {ptrs}"
    # ring order: slot i and slot i+2 share a buffer
    assert ptrs[0::2] == [ptrs[0]] * 3 and ptrs[1::2] == [ptrs[1]] * 3


def test_streamer_prefetch_hit_miss_accounting():
    reg = get_registry()
    reg.enable()
    try:
        reg.reset()
        s, _ = _streamer(staging_slots=2)
        s.prefetch(0)
        s.take(0)                     # hit
        s.take(1)                     # demand miss
        s.prefetch(2)
        s.prefetch(2)                 # idempotent
        s.take(2)                     # hit
        snap = reg.snapshot()
        assert snap["ds_offload_prefetch_hits_total"] == 2
        assert snap["ds_offload_prefetch_misses_total"] == 1
        fam = snap["ds_offload_relay_bytes_total"]
        per_layer = 4 * 8 * 4 + 8 * 4
        assert fam['{dir="h2d"}'] == 3 * per_layer
        assert snap["ds_offload_relay_seconds"]["count"] == 3
    finally:
        reg.reset()
        reg.disable()


def test_streamer_int8_payload_and_materialize():
    s, np_layers = _streamer(int8=True, quant_block=32)
    s.prefetch(1)
    lp = s.take(1)
    assert set(lp) == {"q", "scale"}
    assert all(a.dtype == jnp.int8 for a in jax.tree.leaves(lp["q"]))
    out = jax.jit(s.materialize)(lp)
    np.testing.assert_allclose(np.asarray(out["w"]), np_layers["w"][1],
                               atol=np.abs(np_layers["w"][1]).max() / 120)
    np.testing.assert_allclose(np.asarray(out["b"]), np_layers["b"][1],
                               atol=0.02)


def test_streamer_prefetch_disabled_is_demand_only():
    reg = get_registry()
    reg.enable()
    try:
        reg.reset()
        s, np_layers = _streamer(prefetch=False)
        s.prefetch(0)                 # no-op
        lp = s.take(0)
        assert float(jax.jit(lambda t: t["w"][0, 0])(lp)) == \
            float(np_layers["w"][0, 0, 0])
        snap = reg.snapshot()
        assert snap["ds_offload_prefetch_hits_total"] == 0
        assert snap["ds_offload_prefetch_misses_total"] == 1
    finally:
        reg.reset()
        reg.disable()


# ---------------------------------------------------------------------------
# OffloadedOptimizer int8 masters
# ---------------------------------------------------------------------------

def _host_params(rng):
    k1, k2 = jax.random.split(rng)
    return {"w": np.asarray(jax.random.normal(k1, (300,)), np.float32),
            "b": np.asarray(jax.random.normal(k2, (40,)), np.float32)}


def test_int8_masters_step_parity_with_fp32(rng):
    from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer

    params = _host_params(rng)
    opts = {name: OffloadedOptimizer(params, lr=1e-2, int8_masters=int8,
                                     quant_block=64)
            for name, int8 in (("fp32", False), ("int8", True))}
    assert opts["int8"].int8_masters and opts["int8"]._master is None
    gk = jax.random.PRNGKey(3)
    sizes = opts["fp32"]._sizes          # grads follow tree-leaf order
    for step in range(5):
        gk, sub = jax.random.split(gk)
        grads = [np.asarray(jax.random.normal(jax.random.fold_in(sub, j),
                                              (s,)), np.float32)
                 for j, s in enumerate(sizes)]
        outs = {name: opt.step([g.copy() for g in grads])
                for name, opt in opts.items()}
    for a, b in zip(outs["fp32"], outs["int8"]):
        # multi-step drift bound: the int8 code quantizes master AND
        # moments each step
        np.testing.assert_allclose(a, b, rtol=0.1, atol=0.05)
    # the relay payload really is int8 + scales
    q, s = opts["int8"].relay_leaf(0)
    assert q.dtype == np.int8 and s.dtype == np.float32
    assert q.nbytes + s.nbytes < params["w"].nbytes / 2


def test_int8_masters_state_dict_roundtrip(rng):
    from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer

    params = _host_params(rng)
    opt = OffloadedOptimizer(params, lr=1e-2, int8_masters=True,
                             quant_block=64)
    opt.step([np.ones(s, np.float32) for s in opt._sizes])
    sd = opt.state_dict()
    assert sd["master"][0].dtype == np.float32   # format-compatible
    other = OffloadedOptimizer(params, lr=1e-2, int8_masters=True,
                               quant_block=64)
    other.load_state_dict(sd)
    assert other.step_count == opt.step_count
    for i in range(2):
        # dequantized values are exact scale multiples: requant on load
        # reproduces the store
        np.testing.assert_allclose(other._dequant_master(i),
                                   opt._dequant_master(i), rtol=1e-6)
        for a, b in zip(other._dequant_aux(i), opt._dequant_aux(i)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_int8_masters_rejects_nvme():
    from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer

    with pytest.raises(ValueError, match="int8_masters"):
        OffloadedOptimizer({"w": np.ones(8, np.float32)}, backend="nvme",
                           int8_masters=True, swap_dir="/tmp/x")


# ---------------------------------------------------------------------------
# engine integration: streamed + boundary relays
# ---------------------------------------------------------------------------

def _engine(mesh, *, int8_masters=False, int8_stream=False, prefetch=True,
            param_offload=True, gas=1):
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=4, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, max_seq_len=64, remat=False)
    zero = {"stage": 3,
            "offload_optimizer": {"device": "cpu",
                                  "int8_masters": int8_masters,
                                  "quant_block": 64}}
    if param_offload:
        zero["offload_param"] = {"device": "cpu", "prefetch": prefetch,
                                 "int8_stream": int8_stream}
    cfg = {"train_batch_size": 8 * gas, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": gas, "bf16": {"enabled": True},
           "zero_optimization": zero,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "gradient_clipping": 1.0, "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, mesh=mesh, rng=jax.random.PRNGKey(5))
    return engine


def _losses(engine, toks, steps=3):
    out = []
    for _ in range(steps):
        loss = engine.forward((toks, toks))
        engine.step()
        out.append(float(loss))
    return out


def test_prefetch_on_off_loss_identical(mesh8, rng):
    """The streamed transport order must never change the math: the same
    training run with prefetch on and off is bit-identical."""
    set_global_mesh(mesh8)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    on = _losses(_engine(mesh8, prefetch=True), toks)
    off = _losses(_engine(mesh8, prefetch=False), toks)
    assert on == off, (on, off)
    assert on[-1] < on[0]


def test_int8_stream_loss_parity_and_relay_bytes(mesh8, rng):
    """int8 host masters + int8 layer relay: the loss trajectory stays
    within the rtol contract of the fp32-master run, and the H2D layer
    relay ships measurably fewer bytes (the whole point)."""
    set_global_mesh(mesh8)
    reg = get_registry()
    reg.enable()
    try:
        toks = jax.random.randint(rng, (8, 32), 0, 256)
        runs, h2d = {}, {}
        for name, int8 in (("fp32", False), ("int8", True)):
            reg.reset()
            e = _engine(mesh8, int8_masters=int8, int8_stream=int8)
            runs[name] = _losses(e, toks, steps=4)
            # engine state is lazily materialized at the first forward
            assert e._streamed is not None
            assert e._streamed.streamer.int8 == int8
            h2d[name] = reg.snapshot()[
                "ds_offload_relay_bytes_total"]['{dir="h2d"}']
        for a, b in zip(runs["fp32"], runs["int8"]):
            assert abs(a - b) <= 5e-2 * abs(a), (runs["fp32"], runs["int8"])
        assert runs["int8"][-1] < runs["int8"][0]
        # layer payloads halve; embed/head stay bf16, so the total drops
        # by the layers' share (> 1.3x at this tiny arch, ~2x at scale)
        assert h2d["fp32"] / h2d["int8"] > 1.3, h2d
    finally:
        reg.reset()
        reg.disable()


def test_boundary_relay_int8_offload_no_param_tiering(devices, rng):
    """ZeRO-Offload WITHOUT param tiering: the optimizer-boundary relay
    ships int8+scales and dequantizes on device — loss parity with the
    fp32-master engine within rtol, fewer H2D bytes, ds_offload_* series
    populated."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    reg = get_registry()
    reg.enable()
    try:
        toks = jax.random.randint(rng, (8, 32), 0, 256)
        runs, h2d = {}, {}
        for name, int8 in (("fp32", False), ("int8", True)):
            reg.reset()
            e = _engine(mesh, int8_masters=int8, param_offload=False)
            assert e._offload and not e._param_offload
            runs[name] = _losses(e, toks, steps=4)
            snap = reg.snapshot()
            h2d[name] = snap["ds_offload_relay_bytes_total"]['{dir="h2d"}']
            assert snap["ds_offload_relay_bytes_total"]['{dir="d2h"}'] > 0
            assert snap["ds_offload_relay_seconds"]["count"] == 4
        for a, b in zip(runs["fp32"], runs["int8"]):
            assert abs(a - b) <= 5e-2 * abs(a), (runs["fp32"], runs["int8"])
        assert runs["int8"][-1] < runs["int8"][0]
        assert h2d["fp32"] / h2d["int8"] > 1.5, h2d
    finally:
        reg.reset()
        reg.disable()


def test_int8_offload_checkpoint_roundtrip(tmp_path, mesh8, rng):
    """write_state/read_state stays format-compatible under int8 masters
    (fp32 on disk; requantized losslessly on load)."""
    set_global_mesh(mesh8)
    toks = jax.random.randint(rng, (8, 32), 0, 256)
    e = _engine(mesh8, int8_masters=True, int8_stream=True)
    _losses(e, toks, steps=2)
    e.save_checkpoint(str(tmp_path), tag="t")
    saved = jax.device_get(e.state.params)
    other = _engine(mesh8, int8_masters=True, int8_stream=True)
    _losses(other, toks, steps=1)
    other.load_checkpoint(str(tmp_path), tag="t")
    for a, b in zip(jax.tree.leaves(saved),
                    jax.tree.leaves(jax.device_get(other.state.params))):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
