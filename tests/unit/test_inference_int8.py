"""int8 serving tests (VERDICT r3 item 5 done-criteria): logits-tolerance
vs bf16, int8 KV-cache decode parity, quantized memory footprint."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.models.quant import (QTensor, dequantize_tree, is_qtensor,
                                        quantize_layer_params, quantize_weight)


@pytest.fixture()
def tiny(devices, rng):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    toks = jax.random.randint(rng, (2, 16), 0, 256)
    params = model.init(rng, toks)
    return model, params, toks


def test_quantize_weight_roundtrip():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)) * 0.1, jnp.float32)
    qt = quantize_weight(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 32)
    err = np.abs(np.asarray(qt.astype(jnp.float32)) - np.asarray(w))
    colmax = np.abs(np.asarray(w)).max(axis=0)
    assert np.all(err <= colmax / 127.0 + 1e-7)  # per-channel quant bound


def test_quantized_params_memory():
    rng = np.random.default_rng(1)
    params = {"layers": {"w": jnp.asarray(
        rng.normal(size=(4, 256, 256)), jnp.bfloat16)},
        "embed": {"tok": jnp.zeros((128, 256), jnp.bfloat16)}}
    q = quantize_layer_params(params)
    assert is_qtensor(q["layers"]["w"])
    assert not is_qtensor(q["embed"]["tok"])  # embeddings stay dense
    assert q["layers"]["w"].nbytes < 0.55 * params["layers"]["w"].nbytes


def test_int8_engine_logits_close_to_bf16(tiny):
    model, params, toks = tiny
    bf = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", max_out_tokens=64), params=params)
    q8 = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="int8", max_out_tokens=64), params=params)
    lb = np.asarray(bf(toks))
    lq = np.asarray(q8(toks))
    # per-channel int8 weights: logits stay close on the softmax scale
    assert np.abs(lq - lb).mean() < 0.1, np.abs(lq - lb).mean()
    # and the stored layer weights really are int8
    assert any(is_qtensor(l) for l in jax.tree.leaves(
        q8._params["layers"], is_leaf=is_qtensor))


def test_int8_generate_matches_bf16_greedy(tiny):
    model, params, toks = tiny
    bf = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", max_out_tokens=64), params=params)
    q8 = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="int8", max_out_tokens=64), params=params)
    out_b = np.asarray(bf.generate(toks, max_new_tokens=12))
    out_q = np.asarray(q8.generate(toks, max_new_tokens=12))
    assert out_b.shape == out_q.shape
    match = (out_b[:, -12:] == out_q[:, -12:]).mean()
    assert match >= 0.75, match  # random tiny model: quant noise may flip a few


def test_int8_kv_cache_generate(tiny):
    model, params, toks = tiny
    bf = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", max_out_tokens=64), params=params)
    qkv = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="bfloat16", quantize_kv_cache=True, max_out_tokens=64),
        params=params)
    out_b = np.asarray(bf.generate(toks, max_new_tokens=12))
    out_q = np.asarray(qkv.generate(toks, max_new_tokens=12))
    assert qkv._cache["k"].dtype == jnp.int8
    match = (out_b[:, -12:] == out_q[:, -12:]).mean()
    assert match >= 0.75, match


def test_int8_weights_plus_int8_kv(tiny):
    model, params, toks = tiny
    eng = InferenceEngine(model, DeepSpeedInferenceConfig(
        dtype="int8", quantize_kv_cache=True, max_out_tokens=64),
        params=params)
    out = eng.generate(toks, max_new_tokens=8)
    assert out.shape[1] == toks.shape[1] + 8
    # int8 KV footprint: (1 + 4/Dh)/2 of bf16 — the tiny fixture's Dh=16
    # pays 25% scale overhead; production Dh=128 pays ~3%
    kv_bytes = sum(eng._cache[k].nbytes for k in ("k", "v", "k_scale",
                                                  "v_scale"))
    Dh = model.config.head_dim
    dense = 2 * eng._cache["k"].size * 2  # bf16 k+v
    expected = (1 + 4 / Dh) / 2
    assert kv_bytes <= expected * dense + 128, (kv_bytes, dense, expected)


def test_dequantize_tree_roundtrip(tiny):
    model, params, _ = tiny
    q = quantize_layer_params(params, model.config)
    dq = dequantize_tree(q, jnp.float32)
    for a, b in zip(jax.tree.leaves(dq), jax.tree.leaves(params)):
        assert a.shape == b.shape
