"""Continuous-batching serving layer (serving/): scheduler unit behavior
(admission, early-EOS slot free, drain ordering) and greedy-decode PARITY —
a mixed-length request set served through the iteration-level scheduler
must produce token-identical outputs to one-at-a-time ``generate()`` calls.
Runs on the CPU mesh at tiny config (tier-1: the serving path is exercised
on every PR).  Engines are module-scoped: compiles dominate tier-1 wall
time on small hosts, and the serving engine is built to be reused across
request waves anyway (that IS the product behavior under test)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.serving import (FINISHED, IterationScheduler, Request,
                                   ServingEngine)


@pytest.fixture(autouse=True)
def _no_unknown_finish_reasons():
    """Tier-1 assertion: ``ds_serve_finished_total{reason="unknown"}`` must
    stay ZERO across the whole serving suite — a nonzero count means a
    release path finished a request without attributing why (a scheduler
    bug signal, per docs/OBSERVABILITY.md), and it must fail loudly here
    rather than ship as a mystery series in production scrapes."""
    from deepspeed_tpu.monitor.metrics import get_registry

    yield
    c = get_registry().get("ds_serve_finished_total",
                           labels={"reason": "unknown"})
    assert c is None or c.value == 0, (
        f"{c.value} request(s) finished with reason='unknown' — some "
        "release path forgot to set finish_reason (unattributed release)")


@pytest.fixture(autouse=True)
def _span_completeness_guard():
    """Tier-1 span-completeness assertion (mirror of the unknown-reason
    guard, for the request tracer): after any test, every request the
    tracer recorded must have reached its terminal finish edge — zero
    timelines remain open once the test's requests are drained, and every
    retained completion carries the terminal data `/requestz` and the
    phase histograms key on.  An open timeline here means some release
    path finished a request without closing its span record."""
    from deepspeed_tpu.monitor.request_trace import PHASES, \
        get_request_tracer

    tracer = get_request_tracer()
    yield
    assert tracer.open_count == 0, (
        f"request timelines left open after the test: "
        f"{tracer.open_ids()} — a release path finished these requests "
        "without recording the terminal finish edge")
    for rec in tracer.completed():
        assert rec["edges"][-1][1] == "finish", rec
        assert "reason" in rec and "latency_s" in rec, rec
        assert set(rec["phases"]) == set(PHASES), rec


# ---------------------------------------------------------------------------
# scheduler unit tests (pure host logic, no jax)
# ---------------------------------------------------------------------------

def _req(n=4, max_new=4, eos=-1):
    return Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                   max_new_tokens=max_new, eos_token_id=eos)


def test_scheduler_fifo_admission():
    s = IterationScheduler(2)
    reqs = [s.submit(_req()) for _ in range(5)]
    admitted = s.admit()
    assert [r.request_id for r in admitted] == [reqs[0].request_id,
                                               reqs[1].request_id]
    assert {r.slot for r in admitted} == {0, 1}
    assert s.num_queued == 3
    assert s.admit() == []  # no free slots -> nothing admitted


def test_scheduler_early_finish_frees_slot_immediately():
    s = IterationScheduler(2)
    reqs = [s.submit(_req()) for _ in range(3)]
    s.admit()
    # the engine contract: finish_reason is attributed BEFORE finish()
    # (an unset reason lands in the "unknown" bug-signal series)
    reqs[0].finish_reason = "eos"
    s.finish(reqs[0])              # early EOS on slot 0
    assert s.free_slots() == [0]
    nxt = s.admit()
    assert len(nxt) == 1 and nxt[0] is reqs[2] and nxt[0].slot == 0
    assert s.num_queued == 0


def test_scheduler_drain_ordering_by_finish_time():
    s = IterationScheduler(3)
    reqs = [s.submit(_req()) for _ in range(3)]
    s.admit()
    for r in (reqs[1], reqs[2], reqs[0]):
        r.finish_reason = "length"
        s.finish(r)
    assert [r.request_id for r in s.finished] == \
        [reqs[1].request_id, reqs[2].request_id, reqs[0].request_id]
    assert not s.has_work
    assert all(r.state == FINISHED for r in reqs)
    # long-lived serving: finished history is drainable (else it grows
    # without bound)
    assert s.drain_finished() == [reqs[1], reqs[2], reqs[0]]
    assert s.finished == [] and s.drain_finished() == []


# ---------------------------------------------------------------------------
# end-to-end serving on the CPU mesh (shared module-scoped engines)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served(devices):
    """(model, params, ref InferenceEngine, ServingEngine) — one compile
    set shared by every e2e test; the serving engine is reused across
    request waves exactly as in production."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    # kv_page_tokens=16 -> 4 pages per 64-token slot window: every e2e
    # test in this module runs the PAGED cache with real multi-page
    # tables (paged_kv_cache defaults on; page indirection is trivial at
    # one page per slot)
    serve = deepspeed_tpu.init_serving(
        model, config={"dtype": "float32", "max_out_tokens": 64,
                       "kv_page_tokens": 16},
        num_slots=2, prefill_chunk=4, decode_block_tokens=3)
    serve.set_params(params)
    return model, params, ref, serve


def _mixed_requests(rng, n=6):
    """Mixed prompt/output lengths: exercises queueing (n > num_slots),
    chunked prefill (prompts > prefill_chunk), and early slot turnover."""
    lens = [3, 5, 9, 12, 4, 7][:n]
    news = [4, 7, 3, 6, 8, 2][:n]
    keys = jax.random.split(rng, n)
    prompts = [np.asarray(jax.random.randint(keys[i], (lens[i],), 0, 256))
               for i in range(n)]
    return prompts, news


def test_continuous_batching_greedy_parity(served, rng):
    """Tokens served through the continuous-batching scheduler (2 slots,
    4-token prefill chunks, per-row decode positions) must equal
    one-at-a-time generate() for every request."""
    _, _, ref, serve = served
    prompts, news = _mixed_requests(rng)
    want = [np.asarray(ref.generate(p[None], max_new_tokens=n,
                                    do_sample=False))[0, len(p):]
            for p, n in zip(prompts, news)]
    reqs = [serve.submit(p, max_new_tokens=n) for p, n in zip(prompts, news)]
    finished = serve.run()
    assert len(finished) >= len(reqs)
    for i, (req, w) in enumerate(zip(reqs, want)):
        np.testing.assert_array_equal(
            np.asarray(req.output_tokens), w,
            err_msg=f"request {i} (prompt {len(prompts[i])}, "
                    f"max_new {news[i]}) diverged from generate()")


def test_serving_early_eos_frees_slot_and_admits_queue(served, rng):
    """A request whose greedy continuation hits EOS early must free its
    slot mid-flight so a queued request is admitted and completes."""
    _, _, ref, serve = served
    prompts, news = _mixed_requests(rng, n=4)
    # request 0's actual first greedy token becomes its EOS -> finishes
    # after ONE token while others still want up to 8
    eos = int(ref.generate(prompts[0][None], max_new_tokens=1)[0, -1])
    base = len(serve.scheduler.finished)
    r0 = serve.submit(prompts[0], max_new_tokens=8, eos_token_id=eos)
    rest = [serve.submit(p, max_new_tokens=8) for p in prompts[1:]]
    finished = serve.run()[base:]
    assert r0.output_tokens == [eos]
    assert finished[0] is r0                      # early-EOS drains first
    assert all(len(r.output_tokens) == 8 for r in rest)
    assert len(finished) == 4


def test_serving_respects_cache_budget(served, rng):
    """A prompt near max_out_tokens truncates generation at the cache
    bound instead of corrupting neighbor slots; oversized prompts raise."""
    _, _, _, serve = served
    prompt = np.asarray(jax.random.randint(rng, (62,), 0, 256))
    req = serve.submit(prompt, max_new_tokens=32)
    serve.run()
    assert req.done
    # cache_len 64: 1 prefill-sampled token + decode up to pos 63 -> 2
    assert 1 <= len(req.output_tokens) <= 2
    # a prompt filling the whole cache emits exactly the prefill token
    full = serve.submit(np.asarray(jax.random.randint(rng, (64,), 0, 256)),
                        max_new_tokens=8)
    serve.run()
    assert full.done and len(full.output_tokens) == 1
    with pytest.raises(ValueError):
        serve.submit(np.zeros(65, np.int32), max_new_tokens=1)


def test_serving_logical_budget_not_physical_rounding(devices):
    """init_kv_cache rounds the physical depth up to a flash-decode block
    multiple; generation bounds must use the LOGICAL max_out_tokens so
    serving emits exactly what generate() would (which never sees the
    rounding).  Pure bookkeeping — no weights/compiles."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    serve = ServingEngine(model, {"dtype": "float32",
                                  "max_out_tokens": 300}, num_slots=1)
    assert serve.cache_len == 512          # physical: rounded to 256-mult
    assert serve.max_out == 300            # logical: the configured budget
    with pytest.raises(ValueError, match="max_out_tokens=300"):
        serve.submit(np.zeros(301, np.int32), max_new_tokens=1)


def test_serving_smoke_single_program(served):
    """Fast smoke: occupancy varies (1 -> 2 -> 1 -> 0 slots) while the
    decode block stays ONE compiled program (static shapes + active mask)."""
    _, _, _, serve = served
    base = len(serve.scheduler.finished)
    serve.submit(np.asarray([1, 2, 3], np.int32), max_new_tokens=6)
    serve.step()
    calls = {"n": 0}
    real = serve._block()

    def counted(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    serve._block_fn = counted
    serve.submit(np.asarray([4, 5], np.int32), max_new_tokens=7)
    serve.run()
    serve._block_fn = real
    assert calls["n"] >= 2          # ran decode blocks through the wrapper
    assert len(serve.scheduler.finished) - base == 2
    assert not serve.scheduler.has_work


def test_serving_metrics_enabled_parity_and_live_endpoints(served, rng):
    """The acceptance loop for the observability layer: with the metrics
    registry ENABLED and the HTTP exporter LIVE (init_serving(
    metrics_port=0) -> ephemeral port), a mixed request wave must (a) stay
    token-identical to sequential generate(), (b) fill the TTFT /
    queue-wait / per-token-decode histograms, and (c) serve /metrics
    (Prometheus text) + /statz (JSON) mid-loop while requests are still
    in flight."""
    import json
    import urllib.error
    import urllib.request

    import deepspeed_tpu
    from deepspeed_tpu.monitor.metrics import get_registry

    _, _, ref, _ = served
    reg = get_registry()
    reg.enable()
    # share the fixture InferenceEngine's weights; the ephemeral-port
    # exporter comes up with the engine
    serve = deepspeed_tpu.init_serving(
        engine=ref, num_slots=2, prefill_chunk=4,
        decode_block_tokens=3, metrics_port=0)
    try:
        reg.reset()                   # this wave only
        prompts, news = _mixed_requests(rng)
        want = [np.asarray(ref.generate(p[None], max_new_tokens=n,
                                        do_sample=False))[0, len(p):]
                for p, n in zip(prompts, news)]
        reqs = [serve.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        # scrape MID-LOOP: step until something is in flight, then GET
        serve.step()
        url = serve.metrics_server.url
        prom = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "# TYPE ds_serve_ttft_seconds histogram" in prom
        assert "ds_serve_queue_wait_seconds_bucket" in prom
        serve.run()
        statz = json.loads(
            urllib.request.urlopen(url + "/statz").read().decode())
        m = statz["metrics"]
        n = len(reqs)
        assert m["ds_serve_ttft_seconds"]["count"] == n
        assert m["ds_serve_queue_wait_seconds"]["count"] == n
        assert m["ds_serve_tpot_seconds"]["count"] == n   # all multi-token
        assert m["ds_serve_decode_tokens_total"] > 0
        assert m["ds_serve_submitted_total"] == n
        reasons = m["ds_serve_finished_total"]
        assert sum(reasons.values()) == n
        assert reasons['{reason="length"}'] == n          # no EOS stops here
        # 404 for anything else
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(url + "/nope")
        # (a) token parity with metrics enabled + exporter live
        for i, (req, w) in enumerate(zip(reqs, want)):
            np.testing.assert_array_equal(
                np.asarray(req.output_tokens), w,
                err_msg=f"request {i} diverged with metrics enabled")
    finally:
        serve.close()                 # stops the exporter (port released)
        assert serve.metrics_server is None
        reg.disable()


def test_request_spans_reconcile_with_latency(served, rng):
    """The ISSUE 7 reconciliation contract: with the request tracer on,
    every finished request's four-phase edge partition must telescope to
    exactly its latency, the ``ds_serve_phase_*_seconds`` histograms must
    see one observation per finished request (same count as the latency
    histogram), and the four phase sums must add up to the latency
    histogram's sum — the aggregate and per-request views agree."""
    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.request_trace import (PHASES,
                                                     get_request_tracer)

    _, _, _, serve = served
    reg = get_registry()
    reg.enable()
    reg.reset()
    tracer = get_request_tracer()
    tracer.reset()
    tracer.enable()
    prompts, news = _mixed_requests(rng)
    reqs = [serve.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    serve.run()
    n = len(reqs)
    by_id = {r["id"]: r for r in tracer.completed()}
    for req in reqs:
        rec = by_id[req.request_id]
        # per-request: the edge partition telescopes to the latency
        assert sum(rec["phases"].values()) == pytest.approx(
            rec["latency_s"], rel=1e-9, abs=1e-12)
        assert rec["latency_s"] == req.t_finish - req.t_submit
        assert rec["reason"] == req.finish_reason
        assert rec["tokens_out"] == len(req.output_tokens)
        # the measured dispatch spans rode along with token counts
        kinds = {s[0] for s in rec["spans"]}
        assert "prefill_chunk" in kinds and "decode_block" in kinds
        assert sum(s[3] for s in rec["spans"]
                   if s[0] == "prefill_chunk") == req.prompt_len
    # aggregate: one observation per request in every phase histogram,
    # and the phase sums reconcile with the latency histogram's sum
    m = reg.snapshot()
    lat = m["ds_serve_request_latency_seconds"]
    assert lat["count"] == n
    phase_sum = 0.0
    for p in PHASES:
        h = m[f"ds_serve_phase_{p}_seconds"]
        assert h["count"] == n, (p, h)
        phase_sum += h["sum"]
    assert phase_sum == pytest.approx(lat["sum"], rel=1e-9)
    # the tail-attribution summary is non-degenerate over a real wave
    ta = tracer.tail_attribution(p=0.5)
    assert ta["tail_n"] >= 1 and ta["dominant_phase"] in PHASES
    assert sum(ta["phase_share"].values()) == pytest.approx(1.0)


def test_requestz_live_endpoint_and_profilez_clock_agreement(served, rng):
    """The ISSUE 7 acceptance e2e: against ONE live serving run,
    ``/requestz?format=perfetto`` and a ``/profilez?steps=N`` capture
    must share a clock domain — the tracer's anchor is stamped at
    ``start_trace`` (source ``trace_session``), the capture summary
    carries the same anchor, and the request spans recorded during the
    capture overlap the capture's ``[window_lo_us, window_hi_us]``
    device window, so both files load in one Perfetto session with
    aligned timelines."""
    import json
    import threading
    import urllib.request

    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.request_trace import get_request_tracer
    from deepspeed_tpu.profiling.device_trace import perfetto_supported

    if not perfetto_supported():
        pytest.skip("this jax's start_trace has no create_perfetto_trace")
    _, _, ref, _ = served
    reg = get_registry()
    reg.enable()
    serve = deepspeed_tpu.init_serving(
        engine=ref, num_slots=2, prefill_chunk=4, decode_block_tokens=3,
        metrics_port=0, request_trace=True)
    tracer = get_request_tracer()
    tracer.reset()
    stop = threading.Event()

    def waves():
        while not stop.is_set():
            for _ in range(2):
                serve.submit(np.asarray([1, 2, 3], np.int32),
                             max_new_tokens=5)
            serve.run()

    t = threading.Thread(target=waves, daemon=True)
    t.start()
    try:
        url = serve.metrics_server.url
        with urllib.request.urlopen(
                f"{url}/profilez?steps=3&timeout=120", timeout=150) as r:
            summary = json.load(r)
        with urllib.request.urlopen(
                f"{url}/requestz?format=perfetto", timeout=10) as r:
            trace = json.load(r)
    finally:
        stop.set()
        t.join(timeout=30)
        serve.close()
    # both surfaces carry the SAME trace-session anchor
    assert summary["clock"]["source"] == "trace_session"
    other = trace["otherData"]
    assert other["clock_source"] == "trace_session"
    assert other["clock_anchor_unix"] == summary["clock"]["anchor_unix"]
    # clock-domain agreement: request spans recorded while the capture
    # was open land inside (overlap) the capture's device window, in the
    # file's own microsecond domain — the one-Perfetto-session contract
    lo, hi = summary["window_lo_us"], summary["window_hi_us"]
    assert hi > lo
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert xs, "no request spans exported during a live run"
    overlapping = [e for e in xs
                   if e["ts"] < hi and e["ts"] + e["dur"] > lo]
    assert overlapping, (
        f"no request span overlaps the capture window [{lo}, {hi}]us — "
        f"the /requestz and /profilez clock domains diverged")


@pytest.mark.parametrize("position,fused", [("learned", False),
                                            ("rope", False),
                                            ("alibi", True)])
def test_continuous_batching_parity_other_paths(devices, rng, position,
                                                fused):
    """Per-row positions must stay exact for every position scheme AND on
    both decode implementations: the fused Pallas decode_step (per-row
    kernel mask/clamp) and the unfused forward_with_cache vector branch
    (per-row gather/scatter).  The main parity test covers rope+fused."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False, position=position,
                      max_seq_len=64)
    prompts, news = _mixed_requests(rng, n=3)
    params = model.init(rng, jnp.asarray(prompts[0])[None])
    cfg = {"dtype": "float32", "max_out_tokens": 64,
           "use_fused_decode": fused, "kv_page_tokens": 16}
    ref = deepspeed_tpu.init_inference(model, config=cfg)
    ref.set_params(params)
    want = [np.asarray(ref.generate(p[None], max_new_tokens=n,
                                    do_sample=False))[0, len(p):]
            for p, n in zip(prompts, news)]
    serve = deepspeed_tpu.init_serving(
        model, config=cfg, num_slots=2, prefill_chunk=4,
        decode_block_tokens=3)
    serve.set_params(params)
    assert (serve.engine._dparams is not None) == fused
    reqs = [serve.submit(p, max_new_tokens=n) for p, n in zip(prompts, news)]
    serve.run()
    for i, (req, w) in enumerate(zip(reqs, want)):
        np.testing.assert_array_equal(np.asarray(req.output_tokens), w,
                                      err_msg=f"{position} request {i}")
