"""ServingEngine.drain() + /healthz readiness (docs/RESILIENCE.md; the
router drain signal of ROADMAP item 3).

Acceptance: drain completes every in-flight request TOKEN-IDENTICALLY to
sequential generate(), admits nothing new for the whole window, and the
live metrics server's /healthz reports not-ready throughout — verified
against a real HTTP server with a concurrent poller."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.health import get_health
from deepspeed_tpu.monitor.metrics import get_registry


@pytest.fixture(scope="module")
def ref_engine():
    """Shared weights + a reference InferenceEngine for greedy parity."""
    devs = jax.devices()
    mesh = build_mesh(fsdp=8, devices=devs)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    return ref


@pytest.fixture(autouse=True)
def _health_reset():
    yield
    get_health().set_ready()


def _get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_drain_e2e_token_identical_healthz_not_ready(ref_engine, rng):
    reg = get_registry()
    reg.enable()
    flight = get_flight_recorder()
    flight.reset()
    flight.enable()
    serve = deepspeed_tpu.init_serving(
        engine=ref_engine, num_slots=2, prefill_chunk=4,
        decode_block_tokens=3, metrics_port=0)
    try:
        url = serve.metrics_server.url
        code, body = _get(url + "/healthz")
        assert code == 200 and body["ready"] is True

        prompts = [np.asarray(p, np.int32) for p in
                   ([3, 5, 7], [11, 13, 17, 19], [23, 29], [31, 37, 41])]
        news = [12, 9, 11, 8]
        want = [np.asarray(ref_engine.generate(
                    p[None], max_new_tokens=n, do_sample=False))[0, len(p):]
                for p, n in zip(prompts, news)]
        reqs = [serve.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        serve.step()                       # admit the first two slots
        inflight = {r.request_id for r in (serve.scheduler.running()
                                           + serve.scheduler.prefilling())}
        assert len(inflight) == 2

        statuses = []
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                try:
                    statuses.append(_get(url + "/healthz", timeout=2)[0])
                except Exception:
                    pass
                time.sleep(0.002)

        t = threading.Thread(target=poll, daemon=True)
        t.start()
        finished = serve.drain()
        stop.set()
        t.join(timeout=10)

        # every in-flight request finished, token-identically
        assert {r.request_id for r in finished} == inflight
        done_ids = {id(r) for r in finished}
        for req in finished:
            i = next(j for j, r in enumerate(reqs) if r is req)
            np.testing.assert_array_equal(
                np.asarray(req.output_tokens), want[i],
                err_msg=f"request {i} diverged across drain")
        # nothing new was admitted: the never-admitted pair is still queued
        assert serve.scheduler.num_queued == 2
        assert all(r.state == "queued" for r in reqs
                   if id(r) not in done_ids)

        # not-ready for the WHOLE window: observed live mid-drain, and
        # still 503 after (the process is about to go away)
        assert 503 in statuses, f"poller never saw 503 in {statuses[:20]}"
        code, body = _get(url + "/healthz")
        assert code == 503 and body["ready"] is False
        assert body["reason"] == "draining"
        # admission stays closed until an explicit resume
        with pytest.raises(RuntimeError, match="drain"):
            serve.submit(np.asarray([1, 2], np.int32), max_new_tokens=4)
        # run() with admission paused and only queued work RETURNS
        # (queued requests cannot be admitted) instead of spinning
        t1 = time.perf_counter()
        serve.run()
        assert time.perf_counter() - t1 < 5
        assert serve.scheduler.num_queued == 2
        # the draining gauge flipped back to 0 and is exported
        assert reg.gauge("ds_serve_draining").value == 0
        prom = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "ds_serve_draining 0" in prom
        # flight events bracket the window with the request ids
        ev = {e["kind"]: e for e in flight.events()}
        assert ev["serve_drain_start"]["occupied"] == 2
        assert set(ev["serve_drain_start"]["rids"]) == inflight
        assert ev["serve_drain_done"]["finished"] == 2
        assert ev["serve_drain_done"]["timed_out"] is False

        # resume: readiness returns, the queued pair completes with the
        # same tokens generate() would produce
        serve.resume_admission()
        assert _get(url + "/healthz")[0] == 200
        serve.run()
        for i, req in enumerate(reqs):
            assert req.done
            np.testing.assert_array_equal(np.asarray(req.output_tokens),
                                          want[i])
    finally:
        serve.close()
        flight.disable()
        reg.disable()


def test_drain_idle_engine_is_immediate_and_reversible(ref_engine):
    serve = deepspeed_tpu.init_serving(engine=ref_engine, num_slots=2,
                                       prefill_chunk=4,
                                       decode_block_tokens=3)
    assert serve.drain() == []
    assert not get_health().ready
    with pytest.raises(RuntimeError):
        serve.submit(np.asarray([1], np.int32), max_new_tokens=2)
    serve.resume_admission()
    assert get_health().ready
    req = serve.submit(np.asarray([1, 2], np.int32), max_new_tokens=3)
    serve.run()
    assert req.done


def test_drain_timeout_returns_partial(ref_engine):
    """timeout=0 stops the loop before any step: nothing finishes, the
    in-flight request stays live, and the window is flagged timed_out."""
    flight = get_flight_recorder()
    flight.reset()
    flight.enable()
    serve = deepspeed_tpu.init_serving(engine=ref_engine, num_slots=2,
                                       prefill_chunk=4,
                                       decode_block_tokens=3)
    try:
        req = serve.submit(np.asarray([5, 6, 7], np.int32),
                           max_new_tokens=6)
        serve.step()
        t0 = time.perf_counter()
        finished = serve.drain(timeout=0)
        assert time.perf_counter() - t0 < 5
        assert finished == [] and not req.done
        ev = [e for e in flight.events() if e["kind"] == "serve_drain_done"]
        assert ev and ev[-1]["timed_out"] is True
        # the engine still works: resume and finish the request
        serve.resume_admission()
        serve.run()
        assert req.done
    finally:
        flight.disable()
