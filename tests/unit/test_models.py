"""Model-family tests (reference analog: tests/unit/model parity suites,
SURVEY.md §4 — tiny models, numerics vs reference implementations)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm, cross_entropy, get_model_config
from deepspeed_tpu.models.transformer import CausalLM


@pytest.fixture()
def tiny_batch(rng):
    toks = jax.random.randint(rng, (4, 128), 0, 1000)
    return toks


def test_llama_forward_shapes(devices, rng, tiny_batch):
    mesh = build_mesh(dp=2, fsdp=2, tp=2, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh)
    params = model.init(rng, tiny_batch)
    logits = jax.jit(model.apply)(params, tiny_batch)
    assert logits.shape == (4, 128, model.config.vocab_size)
    loss = jax.jit(lambda p, t: model.apply(p, t, labels=t))(params, tiny_batch)
    assert np.isfinite(float(loss))
    # loss at init ~= ln(V)
    assert abs(float(loss) - np.log(model.config.vocab_size)) < 1.0


def test_gpt2_family(devices, rng, tiny_batch):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("gpt2-small", mesh=mesh, num_layers=2, hidden_size=128,
                      intermediate_size=512, num_heads=4, vocab_size=1024)
    params = model.init(rng, tiny_batch)
    assert "pos" in params["embed"]          # learned positions
    assert "lm_head" not in params           # tied embeddings
    assert "bias" in params["layers"]["attn_norm"]  # layernorm
    loss = jax.jit(lambda p, t: model.apply(p, t, labels=t))(params, tiny_batch)
    assert np.isfinite(float(loss))


def test_scan_vs_loop_parity(devices, rng, tiny_batch):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    m_scan = causal_lm("llama-tiny", mesh=mesh, num_layers=2, scan_layers=True,
                       remat=False)
    m_loop = causal_lm("llama-tiny", mesh=mesh, num_layers=2, scan_layers=False,
                       remat=False)
    params = m_scan.init(rng, tiny_batch)
    a = jax.jit(m_scan.apply)(params, tiny_batch)
    b = jax.jit(m_loop.apply)(params, tiny_batch)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_remat_grad_parity(devices, rng, tiny_batch):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    m_remat = causal_lm("llama-tiny", mesh=mesh, num_layers=2, remat=True)
    m_plain = causal_lm("llama-tiny", mesh=mesh, num_layers=2, remat=False)
    params = m_remat.init(rng, tiny_batch)
    g1 = jax.jit(jax.grad(lambda p: m_remat.apply(p, tiny_batch, labels=tiny_batch)))(params)
    g2 = jax.jit(jax.grad(lambda p: m_plain.apply(p, tiny_batch, labels=tiny_batch)))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_cross_entropy_ignore_index():
    logits = jnp.zeros((2, 4, 8))
    labels = jnp.array([[1, 2, -100, 3], [0, -100, -100, 5]])
    loss = cross_entropy(logits, labels)
    # uniform logits -> ln(8) over the 5 valid tokens
    np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-6)


def test_logical_pspecs_match_params(devices, rng, tiny_batch):
    mesh = build_mesh(tp=2, fsdp=4, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh)
    params = model.init(rng, tiny_batch)
    specs = model.logical_pspecs()
    from jax.sharding import PartitionSpec as P
    jax.tree.map(lambda p, s: None, params, specs,
                 is_leaf=lambda x: isinstance(x, P))  # same structure or raises


def test_tp_sharded_training_step(devices, rng, tiny_batch):
    """End-to-end grad step with tp=2 × fsdp=4 sharded params."""
    import optax
    from deepspeed_tpu.runtime.zero.partition import params_pspecs, shardings_from_pspecs

    mesh = build_mesh(tp=2, fsdp=4, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh)
    params = model.init(rng, tiny_batch)
    specs = params_pspecs(params, mesh, shard=True,
                          logical_specs=model.logical_pspecs())
    shardings = shardings_from_pspecs(specs, mesh)
    params = jax.device_put(params, shardings)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s, t):
        loss, g = jax.value_and_grad(lambda pp: model.apply(pp, t, labels=t))(p)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    l0 = None
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tiny_batch)
        l0 = l0 or float(loss)
    assert float(loss) < l0  # optimizes


def test_dropout_active_and_deterministic_off(devices, rng, tiny_batch):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, dropout=0.5)
    params = model.init(rng, tiny_batch)
    k1, k2 = jax.random.split(rng)
    f = jax.jit(lambda p, t, r: model.apply(p, t, rngs={"dropout": r}))
    a = f(params, tiny_batch, k1)
    b = f(params, tiny_batch, k2)
    assert not np.allclose(np.asarray(a), np.asarray(b))  # dropout is live
    # no rng -> deterministic
    g = jax.jit(lambda p, t: model.apply(p, t))
    np.testing.assert_array_equal(np.asarray(g(params, tiny_batch)),
                                  np.asarray(g(params, tiny_batch)))


def test_blockwise_cross_entropy_parity(devices, rng, tiny_batch):
    """Blockwise (chunked, remat) CE == dense CE in loss AND gradients,
    including ignore_index, masking, z_loss, and a chunk that doesn't divide
    the token count."""
    from deepspeed_tpu.models.transformer import blockwise_cross_entropy

    B, S, D, V = 2, 33, 16, 64
    k1, k2, k3 = jax.random.split(rng, 3)
    x = jax.random.normal(k1, (B, S, D), jnp.float32)
    head = jax.random.normal(k2, (D, V), jnp.float32) * 0.2
    labels = jax.random.randint(k3, (B, S), 0, V)
    labels = labels.at[0, 5].set(-100)
    mask = jnp.ones((B, S), jnp.int32).at[1, 10].set(0)

    def dense(x, head):
        return cross_entropy(x @ head, labels, z_loss=1e-4, mask=mask)

    def blockwise(x, head):
        return blockwise_cross_entropy(x, head, labels, chunk=16, z_loss=1e-4,
                                       mask=mask)

    ld, (gxd, ghd) = jax.value_and_grad(dense, argnums=(0, 1))(x, head)
    lb, (gxb, ghb) = jax.jit(jax.value_and_grad(blockwise, argnums=(0, 1)))(x, head)
    np.testing.assert_allclose(float(ld), float(lb), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gxd), np.asarray(gxb), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ghd), np.asarray(ghb), rtol=1e-5, atol=1e-6)


def test_model_ce_chunk_matches_dense(devices, rng, tiny_batch):
    """End-to-end: model loss with ce_chunk forced equals the dense path."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    m_dense = causal_lm("llama-tiny", mesh=mesh, num_layers=2, ce_chunk=0)
    m_block = causal_lm("llama-tiny", mesh=mesh, num_layers=2, ce_chunk=64)
    params = m_dense.init(rng, tiny_batch)
    l1 = jax.jit(lambda p: m_dense.apply(p, tiny_batch, labels=tiny_batch))(params)
    l2 = jax.jit(lambda p: m_block.apply(p, tiny_batch, labels=tiny_batch))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


def test_activation_checkpointing_config_wires_remat(devices, rng):
    """ds_config activation_checkpointing toggles the model's remat flag."""
    import deepspeed_tpu

    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    for section, expect in (({"enabled": True, "policy": "dots"}, True),
                            ({"enabled": False}, False),
                            ({"partition_activations": True}, True),
                            (None, None)):
        model = causal_lm("llama-tiny", mesh=mesh, num_layers=2)
        assert model.config.remat is None
        cfg = {"train_batch_size": 8, "steps_per_print": 10**9}
        if section is not None:
            cfg["activation_checkpointing"] = section
        deepspeed_tpu.initialize(model=model, config=cfg, mesh=mesh)
        assert model.config.remat is expect
        if section and section.get("policy"):
            assert model.config.remat_policy == section["policy"]
