"""Step-time watchdog units: median tracking, one-shot arming, no
re-trigger storm, the falling-median re-anchor, and the steady-state cost
contract (one deque append + one comparison + a countdown — no suspect-path
median recompute, no per-call allocation growth)."""

import sys

import pytest

from deepspeed_tpu.monitor.watchdog import StepWatchdog


def test_median_tracking_and_trip():
    wd = StepWatchdog(factor=10.0, window=16, warmup=4)
    for _ in range(8):
        assert wd.observe(0.1) is False
    assert wd.median == pytest.approx(0.1)
    # 5x median: suspect, but below factor -> no trip, bound refreshed
    assert wd.observe(0.5) is False
    assert not wd.fired
    # 20x median: trips exactly once, with the anomaly excluded from its
    # own median
    assert wd.observe(2.0) is True
    assert wd.fired
    assert wd.last_trip["median"] == pytest.approx(0.1, rel=0.3)
    assert wd.last_trip["ratio"] > 10.0


def test_one_shot_no_retrigger_storm():
    wd = StepWatchdog(factor=10.0, window=16, warmup=4)
    for _ in range(6):
        wd.observe(0.1)
    assert wd.observe(5.0) is True
    # a stalled run keeps producing slow steps: NONE of them re-trip
    for _ in range(20):
        assert wd.observe(5.0) is False
    assert wd.fired
    # reset re-arms (fresh warmup)
    wd.reset()
    assert not wd.fired
    for _ in range(6):
        wd.observe(0.1)
    assert wd.observe(5.0) is True


def test_warmup_never_trips():
    wd = StepWatchdog(factor=10.0, window=16, warmup=8)
    # wild variance during warmup (compiles!) must not fire
    for v in (10.0, 0.1, 5.0, 0.1, 0.1, 0.1, 0.1):
        assert wd.observe(v) is False
    assert not wd.fired


def test_median_drift_refreshes_bound():
    """A workload that legitimately slows (longer seqs) raises the bound
    via the suspect path instead of firing."""
    wd = StepWatchdog(factor=10.0, window=8, warmup=4)
    for _ in range(8):
        wd.observe(0.1)
    for _ in range(8):
        assert wd.observe(0.4) is False   # 4x: suspects, never trips
    assert not wd.fired
    # the new normal is cheap again: 0.4-based median, 0.5 doesn't suspect
    before = wd.median_recomputes
    assert wd.observe(0.45) is False
    assert wd.median_recomputes == before


def test_falling_median_still_trips():
    """Compile-inflated warmup must not park the bound out of reach: after
    the median falls to the real step time (and a window of fast samples
    re-anchors the bound), a genuine stall vs the NEW median trips.
    Observed live before the fix: 2s compile warmup -> 20s bound; a 3s
    stall at 150x the 20ms steady median never fired."""
    wd = StepWatchdog(factor=10.0, window=8, warmup=3)
    for _ in range(3):
        wd.observe(2.0)            # compiles dominate warmup
    for _ in range(10):            # > window fast steps: bound re-anchors
        assert wd.observe(0.02) is False
    assert wd.bound_refreshes >= 1
    assert wd.observe(3.0) is True # 150x the fast median
    assert wd.last_trip["median"] == pytest.approx(0.02)


def test_steady_state_cost_contract():
    """After warmup, observe() is one append + one comparison: zero median
    recomputes across steady traffic, method rebound to the steady path,
    and no per-call allocation growth (PR 2 getallocatedblocks style)."""
    wd = StepWatchdog(factor=10.0, window=64, warmup=5)
    v = 0.1
    for _ in range(10):
        wd.observe(v)
    assert wd.observe == wd._observe_steady  # warmup branch is GONE
    assert wd.median_recomputes == 0
    vals = [v] * 5000
    before = sys.getallocatedblocks()
    for x in vals:
        wd.observe(x)
    delta = sys.getallocatedblocks() - before
    assert wd.median_recomputes == 0, "steady state must not sort"
    assert delta < 100, f"per-call allocation on the steady path: {delta}"


def test_bad_factor_rejected():
    with pytest.raises(ValueError):
        StepWatchdog(factor=1.0)


def test_warmup_clamped_to_window():
    """warmup > window could never arm (the deque caps at window samples)
    — it must clamp instead of silently disarming the watchdog."""
    wd = StepWatchdog(factor=10.0, window=4, warmup=16)
    assert wd.warmup == 4
    for _ in range(6):
        wd.observe(0.1)
    assert wd.observe == wd._observe_steady   # armed
    assert wd.observe(5.0) is True


def test_engine_trip_one_capture_one_dump(tmp_path):
    """ISSUE 5 acceptance: an injected 10x slow step triggers exactly ONE
    flight-recorder dump and arms exactly ONE post-anomaly trace capture;
    further slow steps don't re-trigger."""
    import glob
    import os

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
    from deepspeed_tpu.profiling.trace import perfetto_supported
    from tests.unit.simple_model import SimpleModel, random_dataset

    x, y = random_dataset(n=16)
    dump_dir = str(tmp_path / "flight")
    trace_dir = str(tmp_path / "wd_trace")
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "flight_recorder": {"enabled": True, "dump_dir": dump_dir},
           "watchdog": {"enabled": True, "factor": 5.0, "warmup": 3,
                        "window": 16, "capture_steps": 1,
                        "output_path": trace_dir},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8), config=cfg,
        rng=jax.random.PRNGKey(0))
    assert engine._watchdog is not None
    rec = get_flight_recorder()
    try:
        def one_step():
            loss = engine.forward((x[:8], y[:8]))
            engine.backward(loss)
            engine.step()

        for _ in range(6):           # warmup + steady median
            one_step()
        assert not engine._watchdog.fired
        # inject a 10x-slow step: backdate the boundary clock so the next
        # observed dt dwarfs the median
        engine._wd_last_t -= 50.0
        one_step()
        assert engine._watchdog.fired
        dumps = glob.glob(os.path.join(dump_dir, "ds_flight_*.json"))
        assert len(dumps) == 1, dumps
        armed = engine._aux_trace
        if perfetto_supported():
            assert armed is not None and armed[1] == "watchdog"
        # keep stepping: no re-trigger storm — still exactly one dump, and
        # the armed capture closes into a summary
        for _ in range(3):
            one_step()
        assert len(glob.glob(os.path.join(dump_dir,
                                          "ds_flight_*.json"))) == 1
        if perfetto_supported():
            assert engine._aux_trace is None
            assert os.path.exists(os.path.join(
                trace_dir, "ds_watchdog_summary.json"))
    finally:
        rec.disable()
        rec.reset()
