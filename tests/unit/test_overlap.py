"""Layer-chunked compute/collective overlap tests (ISSUE 6 tentpole).

Covers: loss + grad-norm parity overlap-on vs overlap-off across ZeRO
stages 1/2/3 (multi-step, tight rtol — same seeds, same math, different
schedule), bucket-grouping units (every param leaf in exactly one bucket,
layer ranges partition [0, L), order = layer order), the chunked analytic
comm plan (per-bucket entries feeding ds_comm_*), a compiled-HLO assertion
that the schedule emits per-bucket ``ds_comm_all_gather`` scopes (the
CPU-checkable form of the device-trace contract), gating/inertness, and
the batch-form guard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.runtime.zero import overlap as ovl


def tiny_model(mesh, **over):
    kw = dict(num_layers=4, hidden_size=64, intermediate_size=128,
              num_heads=4, vocab_size=256, max_seq_len=64)
    kw.update(over)
    return causal_lm("gpt2-small", mesh=mesh, **kw)


def make_engine(mesh, stage, overlap, bucket_layers=2, gas=2, extra=None,
                model_over=None, materialize=True):
    model = tiny_model(mesh, **(model_over or {}))
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "gradient_clipping": 1.0,
           "zero_optimization": {"stage": stage, "overlap_comm": overlap,
                                 "overlap_bucket_layers": bucket_layers,
                                 "stage3_param_persistence_threshold": 0},
           "steps_per_print": 10**9}
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, mesh=mesh, rng=jax.random.PRNGKey(7))
    if materialize:
        # state init is lazy (zero.Init-equivalent); materialize it so the
        # overlap gate + schedule are resolved before the assertions below
        toks = jnp.zeros((16, 32), jnp.int32)
        engine.lazy_init_from_batch((toks, toks))
    return engine


def train(engine, steps=3, seed=0, batch_form="tuple"):
    rng = np.random.default_rng(seed)
    losses, gnorms = [], []
    for _ in range(steps):
        toks = jnp.asarray(rng.integers(0, 256, size=(16, 32)), jnp.int32)
        batch = ((toks, toks) if batch_form == "tuple"
                 else {"tokens": toks, "labels": toks})
        losses.append(float(engine.train_step(batch)))
        gnorms.append(engine.get_global_grad_norm())
    return losses, gnorms


# ---------------------------------------------------------------------------
# loss parity: overlap on == overlap off, stages 1/2/3
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_loss_parity_on_vs_off(devices, stage):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    off = make_engine(mesh, stage, overlap=False)
    l_off, g_off = train(off)
    on = make_engine(mesh, stage, overlap=True)
    assert on._overlap, on._overlap_reason
    l_on, g_on = train(on)
    # same seeds, same math, different collective schedule: fp32 compute,
    # so only collective reassociation noise remains
    np.testing.assert_allclose(l_on, l_off, rtol=2e-5)
    np.testing.assert_allclose(g_on, g_off, rtol=1e-4)


def test_loss_parity_masked_uneven_shards(devices):
    """-100 ignore_index labels + a loss_mask distributed UNEVENLY across
    the data shards: the model's loss is a masked mean over the local
    shard, so the overlap path must weight each shard's CE by its valid
    count (ovl `_ce_weight`) to reproduce the GSPMD path's global masked
    mean.  A plain pmean of per-shard means diverges here."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(0, 256, size=(16, 32)), jnp.int32)
    labels = np.array(toks)             # writable copy
    labels[:2] = -100                   # first shard: almost all ignored
    labels[2:, 20:] = -100              # others: partial
    mask = np.ones((16, 32), np.int32)
    mask[4:6] = 0                       # and one shard mostly masked out
    batch = {"tokens": toks, "labels": jnp.asarray(labels),
             "loss_mask": jnp.asarray(mask)}
    losses = {}
    for key, overlap in (("off", False), ("on", True)):
        # materialize=False: the FIRST call is the loss_mask dict batch, so
        # lazy init must tolerate batch keys model.init() doesn't take
        eng = make_engine(mesh, 3, overlap=overlap, materialize=False)
        losses[key] = [float(eng.train_step(batch)) for _ in range(3)]
        if overlap:
            assert eng._overlap, eng._overlap_reason
    np.testing.assert_allclose(losses["on"], losses["off"], rtol=2e-5)


def test_parity_imperative_api_and_dict_batches(devices):
    """The non-fused forward/backward/step path and dict batches run the
    same overlapped schedule (fused vs accum-loop parity is the engine's
    standing contract)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    on = make_engine(mesh, 3, overlap=True, gas=2)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 256, size=(16, 32)), jnp.int32)
    losses = []
    for _ in range(2):
        for _ in range(2):   # gas=2 micro-batches
            loss = on.forward({"tokens": toks, "labels": toks})
            on.backward(loss)
        on.step()
        losses.append(float(loss))
    off = make_engine(mesh, 3, overlap=False, gas=2)
    ref = []
    for _ in range(2):
        for _ in range(2):
            loss = off.forward({"tokens": toks, "labels": toks})
            off.backward(loss)
        off.step()
        ref.append(float(loss))
    np.testing.assert_allclose(losses, ref, rtol=2e-5)


def test_eval_and_checkpoint_roundtrip(devices, tmp_path):
    """Eval runs the standard GSPMD path over the overlap state layout,
    and a checkpoint saved under overlap specs reloads (reshard layout)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 3, overlap=True)
    l0, _ = train(eng, steps=2)
    toks = jnp.asarray(np.arange(16 * 32).reshape(16, 32) % 256, jnp.int32)
    ev = float(eng.eval_batch(iter([(toks, toks)])))
    assert np.isfinite(ev)
    eng.save_checkpoint(str(tmp_path), tag="ov")
    eng2 = make_engine(mesh, 3, overlap=True)
    train(eng2, steps=1, seed=9)       # init + diverge
    eng2.load_checkpoint(str(tmp_path), tag="ov")
    l_resume, _ = train(eng2, steps=1, seed=1)
    l_cont, _ = train(eng, steps=1, seed=1)
    np.testing.assert_allclose(l_resume, l_cont, rtol=1e-5)


# ---------------------------------------------------------------------------
# bucket grouping
# ---------------------------------------------------------------------------


def test_plan_buckets_partitions_layer_range():
    assert ovl.plan_buckets(6, 2) == [(0, 2), (2, 4), (4, 6)]
    assert ovl.plan_buckets(5, 2) == [(0, 2), (2, 4), (4, 5)]
    assert ovl.plan_buckets(4, 1) == [(0, 1), (1, 2), (2, 3), (3, 4)]
    assert ovl.plan_buckets(3, 99) == [(0, 3)]
    # degenerate bucket size clamps to 1
    assert ovl.plan_buckets(2, 0) == [(0, 1), (1, 2)]


def _sched(devices, stage=3, bucket_layers=2, model_over=None):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, stage, overlap=True,
                      bucket_layers=bucket_layers, model_over=model_over)
    assert eng._overlap
    return eng, eng._overlap_sched


def test_every_leaf_in_exactly_one_bucket(devices):
    eng, sched = _sched(devices)
    assign = sched.bucket_assignment()
    params = eng.state.params
    L = sched.L

    # non-layer leaves: exactly one entry, bucketed embed or head
    for key, want in (("embed", "embed"), ("final_norm", "head")):
        for path, _ in jax.tree_util.tree_leaves_with_path(params[key]):
            pid = key + jax.tree_util.keystr(path)
            assert assign.pop(pid) == want
    if "lm_head" in params:
        for path, _ in jax.tree_util.tree_leaves_with_path(
                params["lm_head"]):
            assert assign.pop("lm_head" + jax.tree_util.keystr(path)) \
                == "head"
    # stacked layer leaves: the per-leaf ranges partition [0, L) in order
    ranges = {}
    for pid, bucket in assign.items():
        assert pid.startswith("layers["), pid
        rng_s = pid[len("layers"):].split("]")[0] + "]"
        b0, b1 = map(int, rng_s.strip("[]").split(":"))
        leaf = pid.split("]", 1)[1]
        ranges.setdefault(leaf, []).append((b0, b1))
        assert bucket == f"layers[{b0}:{b1}]"
    assert ranges, "no layer leaves assigned"
    for leaf, rs in ranges.items():
        rs.sort()
        assert rs[0][0] == 0 and rs[-1][1] == L, (leaf, rs)
        for (a0, a1), (b0, b1) in zip(rs, rs[1:]):
            assert a1 == b0, (leaf, rs)   # contiguous, no overlap, ordered


def test_bucket_infos_order_is_layer_order(devices):
    _, sched = _sched(devices, bucket_layers=1)
    infos = sched.bucket_infos()
    assert infos[0].kind == "embed" and infos[-1].kind == "head"
    layer_infos = [i for i in infos if i.kind == "layers"]
    starts = [i.start for i in layer_infos]
    assert starts == sorted(starts)
    assert [(i.start, i.stop) for i in layer_infos] == sched.buckets
    # stage-3 layer buckets are rematerialized: backward re-gathers
    assert all(i.gathers_per_micro == 2 for i in layer_infos)


def test_layerwise_pspecs_never_shard_layer_dim(devices):
    eng, sched = _sched(devices)
    for spec in jax.tree_util.tree_leaves(
            eng._param_specs["layers"],
            is_leaf=lambda s: hasattr(s, "index")):
        entries = tuple(spec)
        assert not entries or entries[0] is None, spec


# ---------------------------------------------------------------------------
# analytic comm plan: chunked entries
# ---------------------------------------------------------------------------


def test_comm_plan_is_per_bucket(devices):
    eng, sched = _sched(devices, bucket_layers=1)
    plan = eng._comm_plan
    assert plan is not None
    gathers = [e for e in plan["micro"] if e[0] == "all_gather"]
    # one gather entry per bucket that holds sharded leaves; 4 layers at
    # bucket=1 plus embed plus head
    assert len(gathers) >= len(sched.buckets)
    # layer buckets are rematerialized: calls count fwd + bwd re-gather
    total_calls = sum(e[1] for e in gathers)
    assert total_calls > 2 * len(sched.buckets)
    # bytes conservation: the chunked entries cover every sharded param
    # byte — layer gathers 2x (fwd+bwd), embed/head 1x
    c_item = jnp.dtype(eng.compute_dtype).itemsize
    from deepspeed_tpu.runtime.zero.overlap import _sharded_dims

    def sharded_bytes(tree, spec_tree):
        total = 0
        flat_p = jax.tree_util.tree_leaves(tree)
        flat_s = jax.tree_util.tree_leaves(
            spec_tree, is_leaf=lambda s: hasattr(s, "index"))
        for leaf, spec in zip(flat_p, flat_s):
            if _sharded_dims(spec, eng.mesh):
                total += int(np.prod(leaf.shape)) * c_item
        return total

    p = eng.state.params
    want = (2 * sharded_bytes(p["layers"], eng._param_specs["layers"])
            + sharded_bytes(p["embed"], eng._param_specs["embed"])
            + sum(sharded_bytes(p[k], eng._param_specs[k])
                  for k in ("final_norm", "lm_head", "lm_head_bias")
                  if k in p))
    assert sum(e[2] for e in gathers) == want
    # hideable fraction is a sane ratio
    assert 0.0 < sched.hideable_comm_fraction() < 1.0


def test_comm_plan_counts_residual_dp_all_reduce(devices):
    """On a dp x fsdp mesh the scatter covers only fsdp; _reduce_tree
    pmeans the rest over dp (ds_comm_all_reduce scopes) — the analytic
    plan must carry matching all_reduce entries, and loss parity must hold
    on that mesh shape too."""
    mesh = build_mesh(dp=2, fsdp=4, devices=devices)
    set_global_mesh(mesh)
    off = make_engine(mesh, 3, overlap=False)
    l_off, _ = train(off, steps=2)
    on = make_engine(mesh, 3, overlap=True)
    assert on._overlap, on._overlap_reason
    l_on, _ = train(on, steps=2)
    np.testing.assert_allclose(l_on, l_off, rtol=2e-5)
    ars = [e for e in on._comm_plan["micro"] if e[0] == "all_reduce"]
    assert ars, ("residual dp pmean missing from the analytic ledger "
                 "(device captures would show ds_comm_all_reduce rows "
                 "against a zero analytic series)")
    assert all(w == 2 for *_, w in ars)   # the dp extent, not dp*fsdp


def test_comm_series_recorded_per_execution(devices):
    from deepspeed_tpu.monitor.metrics import get_registry

    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 3, overlap=True,
                      extra={"comms_logger": {"enabled": True}})
    registry = get_registry()
    registry.reset()
    train(eng, steps=2)
    snap = registry.snapshot()
    assert snap.get("ds_comm_all_gather_calls_total", 0) > 0
    assert snap.get("ds_overlap_buckets", 0) == \
        len(eng._overlap_sched.bucket_infos())
    assert "ds_overlap_hidden_comm_seconds_est" in snap


# ---------------------------------------------------------------------------
# the compiled schedule: per-bucket ds_comm scopes (CPU-checkable form of
# the device-trace contract — scope names land in HLO op metadata, which is
# exactly what the perfetto post-processor matches on device rows)
# ---------------------------------------------------------------------------


def test_compiled_schedule_emits_per_bucket_gather_scopes(devices):
    eng, sched = _sched(devices, bucket_layers=1)
    toks = jnp.zeros((16, 32), jnp.int32)
    txt = eng._accum_fn.lower(eng.state, (toks, toks),
                              jax.random.PRNGKey(0)).compile().as_text()
    n_layer_buckets = len(sched.buckets)
    assert txt.count("ds_comm_all_gather") >= n_layer_buckets
    # the per-bucket lanes are distinguishable in the trace
    for i in range(n_layer_buckets):
        assert f"overlap_b{i}" in txt
    assert "ds_fwd_bwd" in txt


def test_stage2_schedule_emits_reduce_scatter_scopes(devices):
    eng, _ = _sched(devices, stage=2, bucket_layers=1)
    toks = jnp.zeros((16, 32), jnp.int32)
    txt = eng._accum_fn.lower(eng.state, (toks, toks),
                              jax.random.PRNGKey(0)).compile().as_text()
    assert "ds_comm_reduce_scatter" in txt


# ---------------------------------------------------------------------------
# gating / guards
# ---------------------------------------------------------------------------


def test_overlap_inert_on_stage0_warns_and_falls_back(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 0, overlap=True)
    assert not eng._overlap
    assert "zero_optimization.overlap_comm" in eng._inert_config_keys
    train(eng, steps=1)   # GSPMD fallback still trains


def test_overlap_falls_back_without_segments(devices):
    """A model without stream_segments (client flax module) keeps the
    GSPMD path — warn, not crash."""
    from tests.unit.simple_model import SimpleModel, random_dataset

    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    x, y = random_dataset(n=16, dim=16, out_dim=4)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3, "overlap_comm": True},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg, mesh=mesh,
        rng=jax.random.PRNGKey(3))
    loss = float(engine.train_step((x, y)))
    assert not engine._overlap and engine._overlap_reason
    assert np.isfinite(loss)


def test_unroutable_batch_fails_loudly(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 3, overlap=True, gas=1)
    toks = jnp.zeros((8, 16), jnp.int32)
    train(eng, steps=1)   # init with a routable batch first
    with pytest.raises(ValueError, match="overlap_comm"):
        eng.forward((toks, toks, toks))   # ambiguous 3-tuple
