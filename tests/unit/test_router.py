"""Multi-replica router (serving/router.py + tools/router.py): the
no-jax tool selftest wired tier-1, router unit behavior against synthetic
endpoints, and the live two-replica e2e — a shared-prefix trace dispatched
least-loaded over TWO real ServingEngines (each with its own registry,
health flag, serving loop, and ``/generate`` endpoint), one replica
drained mid-trace via the ``/healthz`` signal: every request completes
token-identically to ``generate()`` and none is dropped."""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.health import HealthState
from deepspeed_tpu.monitor.metrics import MetricsRegistry
from deepspeed_tpu.serving import Router, RouterServer

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# offline tool: selftest wired tier-1 + the no-jax contract
# ---------------------------------------------------------------------------

def test_router_tool_selftest():
    """tools/router.py --selftest drives the REAL Router through
    least-loaded picks, session affinity, drain redistribution with zero
    drops, and the HTTP front-end, against two synthetic replicas."""
    router_tool = _tool("router")
    assert router_tool.main(["router", "--selftest"]) == 0


def test_router_tool_runs_without_jax():
    """The router's ONE fresh-interpreter smoke: the STATIC half of the
    no-jax contract is owned by dslint rule DSL003's whole-import-graph
    check (tests/unit/test_dslint.py::test_jax_free_tools_import_graph,
    covering all six operator tools in one pass); this subprocess pins
    the RUNTIME half for router specifically (the selftest asserts on
    sys.modules in a fresh interpreter)."""
    import subprocess

    script = os.path.join(_TOOLS, "router.py")
    proc = subprocess.run(
        [sys.executable, script, "--selftest"], capture_output=True,
        text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert "router selftest: OK" in proc.stdout


# ---------------------------------------------------------------------------
# router units against synthetic replicas (the tool's fixture, reused)
# ---------------------------------------------------------------------------

def test_router_least_loaded_and_inflight_tiebreak():
    """Dispatch follows the live load gauges, and the router's own
    in-flight accounting spreads a burst BETWEEN polls (the /statz view
    is eventually-consistent)."""
    router_tool = _tool("router")
    reps = [router_tool._FakeReplica("a"), router_tool._FakeReplica("b")]
    a, b = reps
    reg = MetricsRegistry().enable()
    router = Router([f"a={a.url}", f"b={b.url}"], registry=reg,
                    dispatch_rounds=3, retry_backoff=0.01)
    try:
        a.queue_depth = 3
        router.refresh()
        picks = [router.pick().name for _ in range(3)]
        assert picks == ["b", "b", "b"]
        # in-flight tiebreak: with b carrying 4 un-acked dispatches, the
        # next pick prefers a (3 queued) over b (0 queued + 4 in flight)
        router._by_name["b"].inflight = 4
        assert router.pick().name == "a"
        router._by_name["b"].inflight = 0
        # unreachable replica drops out of membership on poll
        b.stop()
        router.refresh()
        assert [r.ready for r in router.replicas] == [True, False]
        assert router.pick().name == "a"
        code, body = router.dispatch({"prompt": [1], "max_new_tokens": 2})
        assert code == 200 and body["replica"] == "a"
    finally:
        a.stop()


# ---------------------------------------------------------------------------
# live two-replica e2e on the CPU mesh
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet(devices):
    """(ref InferenceEngine, [replica ServingEngines], Router,
    RouterServer): two real replicas sharing one set of weights, each
    with a PRIVATE registry + health flag (per-replica /statz and
    /healthz truths in one process), background serving loops, and live
    /generate endpoints."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    replicas = []
    for _ in range(2):
        reg = MetricsRegistry().enable()
        serve = deepspeed_tpu.init_serving(
            model, config={"dtype": "float32", "max_out_tokens": 64,
                           "kv_page_tokens": 16},
            num_slots=2, prefill_chunk=8, decode_block_tokens=3,
            metrics_port=0, registry=reg, private_health=True,
            serve_loop=True)
        serve.set_params(params)
        replicas.append(serve)
    assert replicas[0].health is not replicas[1].health
    assert isinstance(replicas[0].health, HealthState)
    router = Router(
        [f"repl{i}={s.metrics_server.url}" for i, s in enumerate(replicas)],
        registry=MetricsRegistry().enable(), dispatch_rounds=8,
        retry_backoff=0.02, poll_interval=0.05)
    router.refresh()
    front = RouterServer(router).start()
    yield ref, replicas, router, front
    front.stop()
    router.stop()
    for s in replicas:
        s.close()


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def test_live_replica_generate_endpoint(fleet, rng):
    """One replica's POST /generate returns generate()-identical tokens
    (the loop thread steps; the HTTP worker blocks on completion)."""
    ref, replicas, _, _ = fleet
    prompt = np.asarray(jax.random.randint(rng, (9,), 0, 256))
    want = np.asarray(ref.generate(prompt[None], max_new_tokens=6,
                                   do_sample=False))[0, 9:]
    code, body = _post(replicas[0].metrics_server.url,
                       {"prompt": prompt.tolist(), "max_new_tokens": 6})
    assert code == 200
    np.testing.assert_array_equal(np.asarray(body["tokens"]), want)
    assert body["finish_reason"] == "length"


def test_two_replica_trace_with_middrain_zero_dropped(fleet, rng):
    """THE acceptance e2e: a bimodal shared-prefix trace through the
    router front-end over two live replicas; replica 0 drains mid-trace
    (its /healthz flips 503 and its /generate starts refusing) — every
    request still completes token-identically to generate(), none are
    dropped, and post-drain traffic lands on replica 1 only."""
    ref, replicas, router, front = fleet
    for s in replicas:
        s.resume_admission()          # clean membership from prior tests
    router.refresh()
    assert sum(r.ready for r in router.replicas) == 2

    keys = jax.random.split(rng, 24)
    shared = np.asarray(jax.random.randint(keys[0], (32,), 0, 256))
    prompts, news = [], []
    for i in range(16):
        if i % 4 == 3:                # bimodal: every 4th is a cold long
            p = np.asarray(jax.random.randint(keys[i + 1], (20,), 0, 256))
            n = 8
        else:                         # shared 2-page prefix + unique tail
            tail = np.asarray(jax.random.randint(keys[i + 1],
                                                 (3 + i % 5,), 0, 256))
            p = np.concatenate([shared, tail])
            n = 3 + i % 4
        prompts.append(p)
        news.append(n)
    want = [np.asarray(ref.generate(p[None], max_new_tokens=n,
                                    do_sample=False))[0, len(p):]
            for p, n in zip(prompts, news)]

    results = [None] * len(prompts)
    errors = []

    def client(i):
        try:
            results[i] = _post(front.url,
                               {"prompt": prompts[i].tolist(),
                                "max_new_tokens": news[i],
                                "session": f"sess-{i % 3}"})
        except Exception as exc:          # noqa: BLE001 - collected below
            errors.append((i, repr(exc)))

    router.start()                        # live membership polling
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads[:8]:
        t.start()
    # drain replica 0 mid-trace on a side thread (the loop keeps
    # stepping; drain only waits) — /healthz flips immediately
    drainer = threading.Thread(target=replicas[0].drain)
    drainer.start()
    for t in threads[8:]:
        t.start()
    for t in threads:
        t.join(timeout=180)
    drainer.join(timeout=180)

    assert not errors, errors
    assert all(r is not None for r in results), "client thread hung"
    # ZERO dropped: every request came back 200 with exact tokens
    by_replica = {"repl0": 0, "repl1": 0}
    for i, (code, body) in enumerate(results):
        assert code == 200, (i, body)
        np.testing.assert_array_equal(
            np.asarray(body["tokens"]), want[i],
            err_msg=f"request {i} diverged through the router "
                    f"(served by {body['replica']})")
        by_replica[body["replica"]] += 1
    assert by_replica["repl1"] > 0
    # replica 0 is out of membership; new traffic goes to replica 1 only
    assert not replicas[0].health.ready
    router.refresh()
    r0 = [r for r in router.replicas if r.name == "repl0"][0]
    assert not r0.ready and "drain" in (r0.reason or "")
    code, body = _post(front.url, {"prompt": prompts[0].tolist(),
                                   "max_new_tokens": news[0]})
    assert code == 200 and body["replica"] == "repl1"
    np.testing.assert_array_equal(np.asarray(body["tokens"]), want[0])
    # the router front /healthz stays ready on one live replica
    with urllib.request.urlopen(front.url + "/healthz", timeout=5) as resp:
        assert json.load(resp)["ready"] is True
    # rejoin: resume_admission flips repl0's private health back
    replicas[0].resume_admission()
    router.refresh()
    assert sum(r.ready for r in router.replicas) == 2
    # per-replica leak probe after the full trace (drain included)
    for s in replicas:
        s.pool.check_no_leak()


def test_replica_scoped_statz_and_health(fleet):
    """The multi-replica-per-process enablers: each replica's /statz is
    ITS registry (disjoint counters) and /healthz is ITS health flag —
    draining one replica must not flip the other's readiness."""
    _, replicas, _, _ = fleet
    for s in replicas:
        s.resume_admission()
    urls = [s.metrics_server.url for s in replicas]
    with urllib.request.urlopen(urls[0] + "/healthz", timeout=5) as resp:
        assert json.load(resp)["ready"] is True
    replicas[0].scheduler.pause_admission()
    replicas[0].health.set_not_ready("draining")
    try:
        code0 = urllib.request.urlopen(
            urls[1] + "/healthz", timeout=5).status
        assert code0 == 200, "replica 1's health flipped with replica 0"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urls[0] + "/healthz", timeout=5)
        assert exc.value.code == 503
    finally:
        replicas[0].resume_admission()

    # disjoint registries: submitting on replica 1 moves only ITS counter
    def submitted(u):
        with urllib.request.urlopen(u + "/statz", timeout=5) as resp:
            return json.load(resp)["metrics"].get(
                "ds_serve_submitted_total", 0)

    base0, base1 = submitted(urls[0]), submitted(urls[1])
    _post(urls[1], {"prompt": [1, 2, 3], "max_new_tokens": 2})
    assert submitted(urls[1]) == base1 + 1
    assert submitted(urls[0]) == base0


def test_http_timeout_aborts_request_and_frees_slot(fleet, rng):
    """A /generate whose client deadline expires gets 504 AND the engine
    tears the abandoned request down at the next step boundary — the
    slot and its pages free instead of decoding to max_new_tokens for
    nobody (review finding: orphan requests must not saturate slots)."""
    import time

    _, replicas, _, _ = fleet
    serve = replicas[1]
    serve.resume_admission()
    prompt = np.asarray(jax.random.randint(rng, (8,), 0, 256)).tolist()
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(serve.metrics_server.url,
              {"prompt": prompt, "max_new_tokens": 48, "timeout": 0.0})
    assert exc.value.code == 504
    assert json.load(exc.value)["error"].startswith("generation timed out")
    deadline = time.monotonic() + 30
    while (serve.scheduler.num_occupied or serve.pool.pages_used) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert serve.scheduler.num_occupied == 0
    assert serve.pool.pages_used == 0
    serve.pool.check_no_leak()
    # the replica still serves normally afterwards
    code, body = _post(serve.metrics_server.url,
                       {"prompt": prompt, "max_new_tokens": 3})
    assert code == 200 and len(body["tokens"]) == 3


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_loop_crash_flips_health_and_requeues(devices):
    """A crashed serving loop must read as a DEAD replica: /healthz flips
    503 (the router stops sending / drops it from membership) and a
    request stuck queued behind the dead loop is handed back 503 after
    the no-progress grace — never a silent zombie (review finding)."""
    import time

    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    # no set_params(): the first step() raises and the loop dies — the
    # engineered stand-in for any fatal step error
    serve = deepspeed_tpu.init_serving(
        model, config={"dtype": "float32", "max_out_tokens": 64,
                       "kv_page_tokens": 16},
        num_slots=1, metrics_port=0, registry=MetricsRegistry().enable(),
        private_health=True, serve_loop=True)
    try:
        url = serve.metrics_server.url
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(url, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                        "timeout": 30})
        assert exc.value.code == 503
        assert json.load(exc.value).get("requeued") is True
        deadline = time.monotonic() + 10
        while serve.health.ready and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not serve.health.ready
        assert "crashed" in (serve.health.reason or "")
        with pytest.raises(urllib.error.HTTPError) as hexc:
            urllib.request.urlopen(url + "/healthz", timeout=5)
        assert hexc.value.code == 503
    finally:
        serve.close()


def test_affinity_crashed_replica_falls_back_and_unpins():
    """A session pinned to a replica that left membership by CRASH (not
    a clean drain, which pops the pin at dispatch) must fall back to
    least-loaded IMMEDIATELY — and drop the pin, so when the crashed
    replica rejoins inside the affinity TTL the conversation stays where
    its prefix pages are now warm instead of bouncing back cold."""
    import time as _time

    router_tool = _tool("router")
    a, b = router_tool._FakeReplica("a"), router_tool._FakeReplica("b")
    try:
        router = Router([f"a={a.url}", f"b={b.url}"],
                        registry=MetricsRegistry().enable(),
                        affinity_ttl=3600.0, retry_backoff=0.01)
        router.refresh()
        b.queue_depth = 5                 # a is least-loaded: pin lands on a
        router.refresh()
        code, body = router.dispatch({"prompt": [1], "max_new_tokens": 2,
                                      "session": "conv"})
        assert code == 200 and body["replica"] == "a"
        assert router._affinity["conv"][0] == "a"
        # a CRASHES (no drain; the pin is still in place when the poll
        # notices) — the next pick must not wait out the hour-long TTL
        a.ready, a.reason = False, None
        a.stop()
        router.refresh()
        assert not router.replicas[0].ready
        b.queue_depth = 0
        picked = router.pick(session="conv")
        assert picked is not None and picked.name == "b"
        # the stale pin is GONE (dropped at pick), and serving the
        # session re-pins it to b
        assert "conv" not in router._affinity
        code, body = router.dispatch({"prompt": [2], "max_new_tokens": 2,
                                      "session": "conv"})
        assert code == 200 and body["replica"] == "b"
        assert router._affinity["conv"][0] == "b"
        # a rejoining does NOT steal the session back (TTL never expired)
        rep_a = router.replicas[0]
        rep_a.ready = True
        assert router.pick(session="conv").name == "b"
    finally:
        b.stop()


def test_affinity_cap_actually_bounds_sessions():
    """The session map is LRU-capped for real: sustained fresh sessions
    inside the TTL cannot grow it past max_sessions (review finding: the
    old bound only dropped TTL-expired entries)."""
    router_tool = _tool("router")
    fake = router_tool._FakeReplica("a")
    try:
        router = Router([f"a={fake.url}"], registry=MetricsRegistry().enable(),
                        max_sessions=8, affinity_ttl=3600.0)
        router.refresh()
        for i in range(20):
            code, _ = router.dispatch({"prompt": [i], "max_new_tokens": 2,
                                       "session": f"sess-{i}"})
            assert code == 200
            assert len(router._affinity) <= 8
        # the survivors are the most recently used sessions
        assert f"sess-19" in router._affinity
        assert f"sess-0" not in router._affinity
    finally:
        fake.stop()


# ---------------------------------------------------------------------------
# distributed tracing: the router's hop log (trace ids minted/honored,
# hops recorded, /requestz exported) against synthetic replicas
# ---------------------------------------------------------------------------

def test_dispatch_mints_trace_and_logs_hops():
    """Every dispatch gets a W3C-shaped trace id (returned in the body
    and honored when the client sends its own traceparent), and the hop
    log records pick/attempt spans — plus retry when the first attempt
    503s — all under that one id."""
    router_tool = _tool("router")
    a, b = router_tool._FakeReplica("a"), router_tool._FakeReplica("b")
    try:
        router = Router([f"a={a.url}", f"b={b.url}"],
                        registry=MetricsRegistry().enable(),
                        dispatch_rounds=3, retry_backoff=0.01)
        router.refresh()
        code, body = router.dispatch({"prompt": [1], "max_new_tokens": 2})
        assert code == 200
        trace = body["trace"]
        assert len(trace) == 32 and int(trace, 16) >= 0
        rec = router.hops.snapshot()["dispatches"][-1]
        assert rec["trace"] == trace and rec["status"] == 200
        kinds = [h["kind"] for h in rec["hops"]]
        assert kinds[0] == "pick" and "attempt" in kinds
        att = [h for h in rec["hops"] if h["kind"] == "attempt"][0]
        assert att["dur_us"] > 0 and att["args"]["status"] == 200

        # an inbound traceparent is honored, not re-minted
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        code, body = router.dispatch({"prompt": [1], "max_new_tokens": 2,
                                      "traceparent": tp})
        assert code == 200 and body["trace"] == "ab" * 16

        # a 503 first attempt -> two attempts + a retry, ONE id
        # (b is loaded so the pick deterministically lands on a first)
        b.queue_depth = 3
        router.refresh()
        a.requeue_next = 1
        code, body = router.dispatch({"prompt": [2], "max_new_tokens": 2})
        assert code == 200
        rec = router.hops.snapshot()["dispatches"][-1]
        assert rec["trace"] == body["trace"]
        kinds = [h["kind"] for h in rec["hops"]]
        assert kinds.count("attempt") == 2 and "retry" in kinds
    finally:
        a.stop()
        b.stop()


def test_router_requestz_endpoint_snapshot_and_perfetto():
    """The router front-end's /requestz: JSON snapshot (with the clock
    anchor the fleet merge translates by) and the perfetto export whose
    envelope matches the replica tracer's contract."""
    router_tool = _tool("router")
    fake = router_tool._FakeReplica("a")
    front = None
    try:
        router = Router([f"a={fake.url}"],
                        registry=MetricsRegistry().enable(),
                        dispatch_rounds=2, retry_backoff=0.01)
        router.refresh()
        code, body = router.dispatch({"prompt": [3], "max_new_tokens": 2})
        assert code == 200
        front = RouterServer(router).start()
        with urllib.request.urlopen(front.url + "/requestz",
                                    timeout=5) as resp:
            snap = json.load(resp)
        assert snap["kind"] == "router_hops"
        assert snap["dispatches_total"] >= 1
        assert set(snap["clock"]) >= {"perf", "unix", "source"}
        assert snap["dispatches"][-1]["trace"] == body["trace"]
        with urllib.request.urlopen(
                front.url + "/requestz?format=perfetto", timeout=5) as resp:
            doc = json.load(resp)
        assert doc["otherData"]["clock_anchor_unix"] == \
            router.hops.anchor["unix"]
        slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert any(e["name"] == "dispatch (200)" for e in slices)
        assert any(e["args"].get("trace") == body["trace"] for e in slices)
        # bad n -> 400, not a stack trace
        try:
            urllib.request.urlopen(front.url + "/requestz?n=zap", timeout=5)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    finally:
        if front is not None:
            front.stop()
        fake.stop()


# ---------------------------------------------------------------------------
# role-split fleets (ISSUE 19): spec parsing, role-scoped picks and
# affinity, the two-phase prefill->decode dispatch with a handoff hop
# ---------------------------------------------------------------------------

def test_role_spec_parsing_and_role_scoped_pick():
    """``name@role=url`` specs land roles on the replicas; ``pick``
    filters by role — ``prefill`` picks are STRICT (a generalist never
    absorbs prefill-phase work), ``decode`` picks accept ``both`` (a
    generalist can always finish a generation), role=None fleets see
    everyone."""
    router_tool = _tool("router")
    p, d, b = (router_tool._FakeReplica(n) for n in "pdb")
    try:
        router = Router([f"p@prefill={p.url}", f"d@decode={d.url}",
                         f"b={b.url}"],
                        registry=MetricsRegistry().enable())
        router.refresh()
        assert [r.role for r in router.replicas] == \
            ["prefill", "decode", "both"]
        assert router._has_roles and router._has_prefill
        assert router.replicas[0].snapshot()["role"] == "prefill"
        # strict prefill: only the prefill replica qualifies
        for _ in range(4):
            assert router.pick(role="prefill").name == "p"
        # decode accepts decode + both
        assert {router.pick(role="decode").name for _ in range(8)} <= \
            {"d", "b"}
        # bad role in the spec is a loud constructor error
        with pytest.raises(ValueError):
            Router([f"x@Frobnicate={p.url}"])
    finally:
        for f in (p, d, b):
            f.stop()


def test_role_scoped_affinity_wrong_role_pin_dropped():
    """Affinity keys are (role, session) in role-split fleets, so one
    session holds one pin PER ROLE — and a pin that somehow points at a
    wrong-role replica (the drained-prefill-absorbs-decode-pins bug
    class) is dropped at pick instead of honored."""
    router_tool = _tool("router")
    p, d = router_tool._FakeReplica("p"), router_tool._FakeReplica("d")
    try:
        router = Router([f"p@prefill={p.url}", f"d@decode={d.url}"],
                        registry=MetricsRegistry().enable(),
                        affinity_ttl=3600.0, retry_backoff=0.01)
        router.refresh()
        code, body = router.dispatch({"prompt": [1, 2], "max_new_tokens": 2,
                                      "session": "conv"})
        assert code == 200 and body["replica"] == "d"
        # tuple keys, one pin per role; NO bare-string key in role fleets
        assert router._affinity[("decode", "conv")][0] == "d"
        assert router._affinity[("prefill", "conv")][0] == "p"
        assert "conv" not in router._affinity
        # poison the decode pin with the prefill replica: the role check
        # at pick drops it and repins to a decode-capable replica
        import time as _time

        router._affinity[("decode", "conv")] = ("p", _time.monotonic())
        picked = router.pick(session="conv", role="decode")
        assert picked is not None and picked.name == "d"
        assert router._affinity.get(("decode", "conv"), (None,))[0] != "p"
    finally:
        p.stop()
        d.stop()


def test_role_split_dispatch_runs_prefill_phase_then_decode():
    """A role-split dispatch is two-phase: the prefill replica gets the
    ``{"phase": "prefill"}`` twin (logged as a ``handoff`` hop), the
    decode replica answers the request itself; a prefill-pool outage
    DEGRADES to monolithic (decode-only) instead of failing."""
    router_tool = _tool("router")
    p, d = router_tool._FakeReplica("p"), router_tool._FakeReplica("d")
    try:
        router = Router([f"p@prefill={p.url}", f"d@decode={d.url}"],
                        registry=MetricsRegistry().enable(),
                        dispatch_rounds=3, retry_backoff=0.01)
        router.refresh()
        code, body = router.dispatch({"prompt": [5, 6, 7],
                                      "max_new_tokens": 2,
                                      "session": "s1"})
        assert code == 200 and body["replica"] == "d"
        assert len(p.served) == 1 and len(d.served) == 1
        assert router.registry.get("ds_router_hops_total",
                                   labels={"kind": "handoff"}).value == 1
        # the hop log carries the phase: a handoff hop names both sides
        last = router.hops.snapshot()["dispatches"][-1]
        kinds = [h["kind"] for h in last["hops"]]
        assert "handoff" in kinds
        hop = next(h for h in last["hops"] if h["kind"] == "handoff")
        assert hop["args"]["prefill"] == "p"
        assert hop["args"]["decode"] == "d"
        # prefill pool dies -> dispatch still answers (decode-only)
        p.ready = False
        router.refresh()
        code, body = router.dispatch({"prompt": [8, 9],
                                      "max_new_tokens": 2})
        assert code == 200 and body["replica"] == "d"
    finally:
        p.stop()
        d.stop()
