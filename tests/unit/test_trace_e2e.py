"""Cross-process distributed tracing e2e (satellite of the tracing PR):
a REAL router process-boundary — the router (this process) in front of
TWO live replica subprocesses, each with its own interpreter, clock
anchor, request tracer, and ``/generate``+``/requestz`` endpoints.

Asserts the two contracts no single-process test can:

- **clock-anchor agreement**: ``fleet_dump --trace`` merges the router's
  ``/requestz``, both replicas' ``/requestz``, and a device capture into
  ONE Perfetto session on the first source's clock, and after the
  per-source unix-anchor shift the winning router ``attempt`` span
  CONTAINS the serving replica's queue/prefill/decode phases;
- **retry-elsewhere under one trace id**: the pinned replica drains
  out-of-band (no router refresh), the next same-session dispatch eats
  its 503 and retries to the survivor — two ``attempt`` spans and a
  ``retry`` instant joined under a single trace id, with the retried
  request's tokens identical to the pre-drain answer (both children
  init from ``PRNGKey(0)``, so the replicas are weight-identical).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from deepspeed_tpu.monitor.metrics import MetricsRegistry
from deepspeed_tpu.serving import Router, RouterServer

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# the replica child: one tiny weight-deterministic ServingEngine on a
# single CPU device, request tracing on, URL handshake on stdout, and a
# file-flag drain trigger (the out-of-band "operator drained it" event)
_CHILD = '''\
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
import jax.numpy as jnp
import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh

mesh = build_mesh(fsdp=1)
set_global_mesh(mesh)
from deepspeed_tpu.models import causal_lm
model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                  intermediate_size=128, num_heads=4, num_kv_heads=2,
                  vocab_size=256, remat=False)
params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
serve = deepspeed_tpu.init_serving(
    model, config={"dtype": "float32", "max_out_tokens": 64,
                   "kv_page_tokens": 16},
    num_slots=2, prefill_chunk=8, decode_block_tokens=3,
    metrics_port=0, serve_loop=True, request_trace=True)
serve.set_params(params)
print("URL", serve.metrics_server.url, flush=True)
drain_flag = sys.argv[1]
while not os.path.exists(drain_flag):
    time.sleep(0.05)
serve.drain()
print("DRAINED", flush=True)
while True:
    time.sleep(1.0)
'''


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def _post(url, payload, timeout=180):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def _wait_unready(url, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            code, _ = _get(url + "/healthz", timeout=5)
        except urllib.error.HTTPError as exc:
            if exc.code == 503:
                return
            raise
        time.sleep(0.1)
    raise AssertionError(f"{url} never flipped unready")


@pytest.fixture(scope="module")
def fleet_procs(tmp_path_factory):
    td = tmp_path_factory.mktemp("trace_e2e")
    script = td / "replica_child.py"
    script.write_text(_CHILD)
    procs, flags = {}, {}
    for name in ("ra", "rb"):
        flags[name] = str(td / f"drain_{name}")
        repo = os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", ".."))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
               "PYTHONPATH": repo + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        procs[name] = subprocess.Popen(
            [sys.executable, str(script), flags[name]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
    urls = {}
    try:
        for name, p in procs.items():
            url, head = None, []
            deadline = time.time() + 300
            while time.time() < deadline:
                line = p.stdout.readline()
                if not line:
                    break
                head.append(line)
                if line.startswith("URL "):
                    url = line.split()[1].strip()
                    break
            assert url, f"replica {name} failed to start:\n" + "".join(head)
            urls[name] = url
            # keep the pipe drained so the child never blocks on stdout
            threading.Thread(target=p.stdout.read, daemon=True).start()
        yield urls, flags
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


def test_retry_elsewhere_one_trace_merged_across_processes(
        fleet_procs, tmp_path):
    urls, flags = fleet_procs
    router = Router([f"ra={urls['ra']}", f"rb={urls['rb']}"],
                    registry=MetricsRegistry().enable(),
                    dispatch_rounds=4, retry_backoff=0.05)
    router.refresh()
    assert sum(r.ready for r in router.replicas) == 2
    front = RouterServer(router).start()
    try:
        payload = {"prompt": list(range(1, 10)), "max_new_tokens": 5,
                   "session": "pin-1"}
        code, body1 = _post(front.url, payload)
        assert code == 200 and body1.get("trace"), body1
        first = body1["replica"]
        other = "rb" if first == "ra" else "ra"

        # drain the session-pinned replica OUT-OF-BAND: the router's
        # membership is stale on purpose (no refresh), so the next
        # dispatch attempts it live and retries off the 503
        open(flags[first], "w").close()
        _wait_unready(urls[first])
        code, body2 = _post(front.url, payload)
        assert code == 200 and body2["replica"] == other, body2
        trace = body2["trace"]
        assert trace and trace != body1["trace"]
        # weight-identical replicas -> token-identical across the retry
        assert body2["tokens"] == body1["tokens"]

        # router-side hop log has the whole story under that one id
        _, snap = _get(front.url + "/requestz")
        rec = [d for d in snap["dispatches"] if d["trace"] == trace]
        assert len(rec) == 1
        kinds = [h["kind"] for h in rec[0]["hops"]]
        assert kinds.count("attempt") == 2
        assert "retry" in kinds and "pick" in kinds

        # ONE merged Perfetto session: router + both replicas + a device
        # capture in ra's clock domain, shifted onto the router's clock
        cap = tmp_path / "devcap.json"
        cap.write_text(json.dumps({"traceEvents": [
            {"ph": "M", "pid": 9, "name": "process_name",
             "args": {"name": "ds_device"}},
            {"ph": "X", "pid": 9, "tid": 1, "name": "fusion.matmul",
             "ts": 10.0, "dur": 40.0}]}))
        out = tmp_path / "merged.json"
        fleet_dump = _tool("fleet_dump")
        rc = fleet_dump.main(["fleet_dump", "--trace",
                              f"router={front.url}",
                              f"ra={urls['ra']}", f"rb={urls['rb']}",
                              f"--capture=ra={cap}", f"--out={out}"])
        assert rc == 0
        merged = json.loads(out.read_text())
        srcs = merged["otherData"]["sources"]
        assert merged["otherData"]["reference"] == "router"
        assert set(srcs) == {"router", "ra", "rb"}
        ev = merged["traceEvents"]
        pnames = {e["args"]["name"] for e in ev
                  if e.get("ph") == "M" and e["name"] == "process_name"}
        assert {"router:ds_router", "ra:ds_requests",
                "rb:ds_requests"} <= pnames
        # the device capture rode ra's anchor shift into the session
        fus = [e for e in ev if e.get("name") == "fusion.matmul"]
        assert len(fus) == 1
        assert fus[0]["ts"] == pytest.approx(
            10.0 + srcs["ra"]["shift_us"], abs=1.0)

        # trace-id join across processes: both attempts in the router's
        # rows, the serving replica's phases in its rows, one id
        def mine(e):
            return (e.get("args") or {}).get("trace") == trace

        attempts = [e for e in ev if e.get("name") == "attempt" and mine(e)]
        assert len(attempts) == 2
        won = [e for e in attempts if e["args"].get("status") == 200]
        assert len(won) == 1
        phases = [e for e in ev if e.get("ph") == "X" and mine(e)
                  and e["name"] in ("queue", "prefill", "decode")]
        assert {e["name"] for e in phases} >= {"queue", "prefill",
                                               "decode"}
        # clock-anchor agreement: on the shared clock the winning
        # attempt CONTAINS the replica's request phases (the 503 attempt
        # contains none — the drained replica admitted nothing).  The
        # tolerance bounds same-host anchor-translation error, far below
        # the attempt's own duration.
        tol = 50_000.0  # us
        lo, hi = won[0]["ts"], won[0]["ts"] + won[0]["dur"]
        for e in phases:
            assert e["ts"] >= lo - tol, (e, lo)
            assert e["ts"] + e["dur"] <= hi + tol, (e, hi)
        # both replicas contributed spans to the one session (the
        # pre-drain request traced on `first`, the retried on `other`)
        assert any((e.get("args") or {}).get("trace") == body1["trace"]
                   for e in ev if e.get("ph") == "X")
    finally:
        front.stop()
        router.stop()
