"""Elastic training resilience (docs/RESILIENCE.md "Elastic training").

THE acceptance e2e: a crash-atomic checkpoint saved at world 4 resumes at
world 2 AND world 8 — ZeRO stages 1/2/3 with plain fp32 state and with
host-offloaded {fp32, int8} masters — with gradient accumulation rescaled
so the global batch is preserved and the loss trajectory equal to an
uninterrupted run.  Plus the chaos-matrix pieces that ride the same
machinery: a bit-flipped shard detected by DEEP verification (per-chunk
sha256, offending shard named) and recovered via walk-back, and
deterministic dataloader stream resume across a batch-size change.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.runtime.checkpoint_engine import atomic
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.testing import chaos
from tests.unit.simple_model import SimpleModel, random_dataset

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")

X, Y = random_dataset(n=64)
TBS = 8                        # micro 1 x world 4 x gas 2
PROBE = (X[:16], Y[:16])


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _make_engine(devs, gas, stage=1, masters=None, ckpt_cfg=None):
    """Engine over the first ``devs`` virtual devices.  ``masters``:
    None = plain fp32 state; "fp32"/"int8" = host-offloaded optimizer
    masters (the PR 10 formats)."""
    mesh = build_mesh(devices=jax.devices()[:devs])
    set_global_mesh(mesh)
    zero = {"stage": stage}
    if masters is not None:
        zero["offload_optimizer"] = {"device": "cpu",
                                     "int8_masters": masters == "int8",
                                     "quant_block": 64}
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": zero,
           "steps_per_print": 10**9}
    if ckpt_cfg:
        cfg["checkpoint"] = ckpt_cfg
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, mesh=mesh,
        rng=jax.random.PRNGKey(3))
    return engine


def _eval_loss(engine):
    engine.eval()
    try:
        return float(engine.forward(PROBE))
    finally:
        engine.train()


def _run_steps(engine, n, start=0):
    """n optimizer steps over a FIXED global-batch schedule (step i always
    consumes the same TBS samples regardless of the engine's gas/world),
    returning the eval-loss trajectory — the world-size-independent
    signal the acceptance compares."""
    out = []
    for i in range(start, start + n):
        gas = engine.config.gradient_accumulation_steps
        per = TBS // gas
        for g in range(gas):
            lo = ((i % 4) * TBS + g * per) % 56
            engine.forward((X[lo:lo + per], Y[lo:lo + per]))
        engine.step()
        out.append(_eval_loss(engine))
    return out


def _init_state(engine, devs):
    """Lazy-init the engine's state from one correctly-sized batch so
    load_checkpoint can reshard over it."""
    engine.forward((X[:devs], Y[:devs]))


# ---------------------------------------------------------------------------
# THE elastic acceptance e2e: save at world 4, resume at 2 and at 8
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stage", [1, 2, 3])
@pytest.mark.parametrize("masters", [None, "fp32", "int8"])
def test_world_size_change_resume_loss_trajectory(tmp_path, stage, masters):
    """Save at world 4 (gas 2), resume at world 2 (gas must become 4) and
    world 8 (gas 1): the eval-loss trajectory equals the uninterrupted
    world-4 run at the matched global batch.  Plain state runs the
    in-program step; offloaded fp32/int8 masters run the host-master
    formats PR 10 added — all resharding through the sharded-load /
    owned-copy seams."""
    save_dir = str(tmp_path)
    reg = get_registry()
    reg.enable()
    try:
        e4 = _make_engine(4, gas=2, stage=stage, masters=masters)
        _run_steps(e4, 2)
        e4.save_checkpoint(save_dir, tag="t")
        ref = _run_steps(e4, 2, start=2)

        for devs, expect_gas in ((2, 4), (8, 1)):
            er0 = reg.counter("ds_elastic_resumes_total").value
            e = _make_engine(devs, gas=2, stage=stage, masters=masters)
            _init_state(e, devs)
            ckpt_dir, _ = e.load_checkpoint(save_dir)
            assert ckpt_dir is not None and ckpt_dir.endswith("t")
            # the divisibility rule resolved gas to preserve global batch 8
            assert e.config.gradient_accumulation_steps == expect_gas
            assert e.config.train_batch_size == TBS
            assert reg.counter("ds_elastic_resumes_total").value - er0 == 1
            got = _run_steps(e, 2, start=2)
            # different device counts reduce/accumulate in a different
            # order: tolerance-equal, not bit-equal
            assert np.allclose(ref, got, rtol=1e-4), (devs, ref, got)
    finally:
        reg.disable()


def test_same_world_resume_stays_exact(tmp_path):
    """Control: a same-topology resume does NOT rescale (no recompile,
    no counter) and the trajectory is exactly the uninterrupted run's."""
    save_dir = str(tmp_path)
    reg = get_registry()
    reg.enable()
    try:
        e = _make_engine(4, gas=2)
        _run_steps(e, 2)
        e.save_checkpoint(save_dir, tag="t")
        ref = _run_steps(e, 2, start=2)
        er0 = reg.counter("ds_elastic_resumes_total").value
        e2 = _make_engine(4, gas=2)
        _init_state(e2, 4)
        ckpt_dir, _ = e2.load_checkpoint(save_dir)
        assert ckpt_dir is not None
        assert e2.config.gradient_accumulation_steps == 2
        assert reg.counter("ds_elastic_resumes_total").value == er0
        assert _run_steps(e2, 2, start=2) == ref
    finally:
        reg.disable()


def test_indivisible_world_raises_with_rule(tmp_path):
    """Global batch 8 at micro 1 cannot resume on a 3-device-dp world:
    the loader raises the documented divisibility rule instead of
    silently training at a different batch size."""
    from deepspeed_tpu.elasticity import ElasticityIncompatibleWorldSize

    save_dir = str(tmp_path)
    e4 = _make_engine(4, gas=2)
    _run_steps(e4, 1)
    e4.save_checkpoint(save_dir, tag="t")
    e3 = _make_engine(3, gas=2)
    _init_state(e3, 3)
    with pytest.raises(ElasticityIncompatibleWorldSize, match="not a"):
        e3.load_checkpoint(save_dir)


def test_elastic_resume_off_keeps_triad(tmp_path):
    """checkpoint.elastic_resume=false: the load succeeds but keeps the
    configured triad (loud warning instead of a silent rescale)."""
    save_dir = str(tmp_path)
    e4 = _make_engine(4, gas=2)
    _run_steps(e4, 1)
    e4.save_checkpoint(save_dir, tag="t")
    e2 = _make_engine(2, gas=2, ckpt_cfg={"elastic_resume": False})
    _init_state(e2, 2)
    ckpt_dir, _ = e2.load_checkpoint(save_dir)
    assert ckpt_dir is not None
    assert e2.config.gradient_accumulation_steps == 2   # untouched


# ---------------------------------------------------------------------------
# chaos matrix: bit-flipped shard -> DEEP-detected, walk-back recovers
# ---------------------------------------------------------------------------


def test_bitflip_shard_deep_detected_and_walked_back(tmp_path):
    """The silent-corruption case only chunk hashes catch: flip a bit in
    a shard and REGENERATE the manifest (corruption arriving before the
    manifest pass — the file-level hashes now agree with the corrupt
    bytes).  ``--deep`` (and ``checkpoint.deep_verify_on_load``) must
    convict the tag NAMING the offending shard, and the loader must walk
    back to the older valid tag — across a world-size change."""
    save_dir = str(tmp_path)
    e4 = _make_engine(4, gas=2,
                      ckpt_cfg={"deep_verify_on_load": True})
    _run_steps(e4, 1)
    e4.save_checkpoint(save_dir, tag="t1")
    ref = _run_steps(e4, 2, start=1)
    e4.save_checkpoint(save_dir, tag="t2")

    t2 = os.path.join(save_dir, "t2")
    shard = glob.glob(os.path.join(t2, "model_states", "shard_p*.bin"))[0]
    chaos.flip_bit(shard)
    atomic.write_manifest(t2, "t2", extra={"world_size": 4})
    # file-level verification now PASSES; only the chunk hashes disagree
    assert atomic.verify_dir(t2, level="full").ok
    probs = atomic.deep_verify(t2)
    assert any("chunk checksum" in p and "shard_p" in p for p in probs)

    # the offline auditor's --deep verdict matches the loader's
    ckpt_verify = _tool("ckpt_verify")
    rep = ckpt_verify.audit(save_dir, level="deep")
    by = {e["tag"]: e["state"] for e in rep["tags"]}
    assert by["t2"] == "corrupt" and by["t1"] == "valid"
    assert rep["loadable"] == "t1"
    assert ckpt_verify.audit(save_dir, level="full")["loadable"] == "t2"

    reg = get_registry()
    reg.enable()
    flight = get_flight_recorder()
    flight.reset()
    flight.enable()
    try:
        fails0 = reg.counter("ds_ckpt_verify_failures_total").value
        e2 = _make_engine(2, gas=2,
                          ckpt_cfg={"deep_verify_on_load": True})
        _init_state(e2, 2)
        ckpt_dir, _ = e2.load_checkpoint(save_dir)   # latest -> t2
        assert ckpt_dir is not None and ckpt_dir.endswith("t1")
        assert reg.counter("ds_ckpt_verify_failures_total").value \
            - fails0 >= 1
        ev = [e for e in flight.events() if e["kind"] == "ckpt_verify_fail"]
        assert ev and ev[-1]["state"] == "corrupt_deep"
        assert any("chunk checksum" in p for p in ev[-1]["problems"])
        # ...and training continues on the walked-back state at the new
        # world (trajectory = the run that never saw t2's corruption)
        got = _run_steps(e2, 2, start=1)
        assert np.allclose(ref, got, rtol=1e-4), (ref, got)

        # deep_verify_on_load is independent of verify_on_load: with the
        # manifest pass OFF, the chunk pass still convicts t2
        e2b = _make_engine(2, gas=2,
                           ckpt_cfg={"verify_on_load": False,
                                     "deep_verify_on_load": True})
        _init_state(e2b, 2)
        ckpt_dir, _ = e2b.load_checkpoint(save_dir)
        assert ckpt_dir is not None and ckpt_dir.endswith("t1")
    finally:
        flight.disable()
        reg.disable()


# ---------------------------------------------------------------------------
# deterministic data resume (dataloader stream state)
# ---------------------------------------------------------------------------


def _loader_ids(loader, n_batches=None):
    out = []
    for batch in loader:
        xs = np.asarray(jax.device_get(batch[0]))
        out.extend(xs[:, 0].tolist())     # first feature identifies the row
        if n_batches is not None and len(out) >= n_batches:
            break
    return out


def test_dataloader_sample_offset_resume_across_batch_size():
    """Consume part of an epoch at batch 8, checkpoint, resume at batch 4
    (the elastic world-change case): the remaining sample stream is
    IDENTICAL — offsets are tracked in samples, and the shuffle
    permutation is a pure function of (seed, epoch)."""
    mesh = build_mesh(devices=jax.devices()[:1])
    a = DeepSpeedDataLoader((X, Y), batch_size=8, mesh=mesh, shuffle=True,
                            seed=7)
    it = iter(a)
    for _ in range(3):
        next(it)                        # 24 samples consumed
    sd = a.state_dict()
    assert sd["samples_consumed"] == 24 and sd["epoch"] == 0

    rest_full = _loader_ids(it)         # the stream an uninterrupted run sees

    b = DeepSpeedDataLoader((X, Y), batch_size=4, mesh=mesh, shuffle=True,
                            seed=7)
    b.load_state_dict(sd)
    rest_resumed = _loader_ids(iter(b))
    assert rest_resumed == rest_full
    # the epoch boundary reset: the NEXT epoch replays from sample 0 with
    # the epoch's own permutation, identically on both loaders
    assert b.state_dict()["epoch"] == 1
    assert b.state_dict()["samples_consumed"] == 0
    a2 = _loader_ids(iter(a))
    b2 = _loader_ids(iter(b))
    assert a2 == b2 and len(b2) == 64


def test_dataloader_resume_validates_identity():
    mesh = build_mesh(devices=jax.devices()[:1])
    a = DeepSpeedDataLoader((X, Y), batch_size=8, mesh=mesh, shuffle=True,
                            seed=7)
    sd = a.state_dict()
    short = DeepSpeedDataLoader((X[:32], Y[:32]), batch_size=8, mesh=mesh,
                                shuffle=True, seed=7)
    with pytest.raises(ValueError, match="length changed"):
        short.load_state_dict(sd)
    reseeded = DeepSpeedDataLoader((X, Y), batch_size=8, mesh=mesh,
                                   shuffle=True, seed=8)
    with pytest.raises(ValueError, match="seed changed"):
        reseeded.load_state_dict(sd)
    # RepeatingLoader passes the state through to its inner loader
    rep = RepeatingLoader(DeepSpeedDataLoader((X, Y), batch_size=8,
                                              mesh=mesh, shuffle=True,
                                              seed=7))
    rep.load_state_dict(sd)
    assert rep.state_dict() == sd


def test_dataloader_state_rides_checkpoint(tmp_path):
    """The engine auto-attaches the training dataloader's stream state to
    client_state.json on save and restores it on load — the missing piece
    that makes elastic resume replay the exact remaining stream."""
    save_dir = str(tmp_path)
    mesh = build_mesh(devices=jax.devices()[:4])
    set_global_mesh(mesh)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10**9}
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, mesh=mesh,
        training_data=(X, Y), rng=jax.random.PRNGKey(3))
    assert isinstance(loader, DeepSpeedDataLoader)
    it = iter(loader)
    for _ in range(3):
        engine.forward(next(it))
        engine.step()
    consumed = loader.state_dict()["samples_consumed"]
    assert consumed == 3 * loader.batch_size
    engine.save_checkpoint(save_dir, tag="t")
    meta = json.load(open(os.path.join(save_dir, "t",
                                       "client_state.json")))
    assert meta["client_state"]["dataloader"]["samples_consumed"] \
        == consumed

    engine2, _, loader2, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, mesh=mesh,
        training_data=(X, Y), rng=jax.random.PRNGKey(3))
    engine2.forward(next(iter(loader2)))
    ckpt_dir, client_state = engine2.load_checkpoint(save_dir)
    assert ckpt_dir is not None
    assert loader2.state_dict()["samples_consumed"] == consumed
    # an explicit caller-provided "dataloader" key wins over the auto one
    engine.save_checkpoint(save_dir, tag="t2",
                           client_state={"dataloader": {"custom": 1}})
    meta = json.load(open(os.path.join(save_dir, "t2",
                                       "client_state.json")))
    assert meta["client_state"]["dataloader"] == {"custom": 1}
