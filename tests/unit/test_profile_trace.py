"""jax.profiler trace capture (VERDICT r3 item 10): wall_clock_breakdown
additionally dumps an xplane trace for a window of steps, with the engine's
phase timers emitted as TraceAnnotation ranges.
"""

import glob
import os

import jax

import deepspeed_tpu
from tests.unit.simple_model import SimpleModel, random_dataset


def test_trace_written_next_to_monitor_output(tmp_path):
    trace_dir = str(tmp_path / "trace")
    x, y = random_dataset(n=16)
    cfg = {"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "wall_clock_breakdown": True,
           "profile_trace": {"start_step": 1, "num_steps": 1,
                             "output_path": trace_dir},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8), config=cfg, rng=jax.random.PRNGKey(0))
    assert engine._trace is not None
    for _ in range(3):
        loss = engine.forward((x[:8], y[:8]))
        engine.backward(loss)
        engine.step()
    assert engine._trace.done
    xplanes = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                        recursive=True)
    assert xplanes, f"no xplane trace under {trace_dir}: " \
                    f"{list(os.walk(trace_dir))}"


def test_trace_disabled_by_default(tmp_path):
    x, y = random_dataset(n=8)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8), config=cfg, rng=jax.random.PRNGKey(0))
    assert engine._trace is None


def test_trace_explicit_enable_without_breakdown(tmp_path):
    trace_dir = str(tmp_path / "trace2")
    x, y = random_dataset(n=8)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "profile_trace": {"enabled": True, "start_step": 1, "num_steps": 1,
                             "output_path": trace_dir},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8), config=cfg, rng=jax.random.PRNGKey(0))
    for _ in range(2):
        engine.forward((x[:8], y[:8]))
        engine.step()
    assert engine._trace.done
