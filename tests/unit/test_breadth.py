"""Breadth-component tests: curriculum, random-LTD, compression, autotuning,
GatheredParameters, hybrid engine (reference: SURVEY.md §2.1 rows 21, 44,
46, 47, 58; zero.Init/GatheredParameters row 9).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from tests.unit.simple_model import SimpleModel, random_dataset


# ---------------------------------------------------------------------------
# curriculum
# ---------------------------------------------------------------------------

def test_curriculum_fixed_linear():
    from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler

    s = CurriculumScheduler({"curriculum_type": "fixed_linear",
                             "min_difficulty": 8, "max_difficulty": 64,
                             "schedule_config": {"total_curriculum_step": 100,
                                                 "difficulty_step": 8}})
    assert s.update_difficulty(0) == 8
    mid = s.update_difficulty(50)
    assert 8 < mid < 64 and mid % 8 == 0
    assert s.update_difficulty(100) == 64
    assert s.update_difficulty(10**6) == 64


def test_curriculum_fixed_discrete_and_truncate():
    from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                     truncate_batch)

    s = CurriculumScheduler({"curriculum_type": "fixed_discrete",
                             "schedule_config": {"difficulty": [16, 32, 64],
                                                 "max_step": [10, 20]}})
    assert s.update_difficulty(5) == 16
    assert s.update_difficulty(15) == 32
    assert s.update_difficulty(25) == 64
    batch = (jnp.ones((2, 64), jnp.int32), jnp.ones((2, 64), jnp.int32))
    out = truncate_batch(batch, 16)
    assert out[0].shape == (2, 16)


# ---------------------------------------------------------------------------
# random-LTD
# ---------------------------------------------------------------------------

def test_random_ltd_bypass_and_restore(rng):
    from deepspeed_tpu.runtime.data_pipeline import random_ltd_layer

    x = jax.random.normal(rng, (2, 16, 8))
    out = random_ltd_layer(lambda t: t * 2.0, x, rng, keep=4)
    # exactly `keep` tokens per row doubled, the rest untouched
    doubled = np.isclose(np.asarray(out), 2 * np.asarray(x)).all(axis=-1)
    untouched = np.isclose(np.asarray(out), np.asarray(x)).all(axis=-1)
    assert (doubled.sum(axis=1) == 4).all()
    assert (untouched.sum(axis=1) == 12).all()
    # full keep = plain layer
    full = random_ltd_layer(lambda t: t * 2.0, x, rng, keep=16)
    np.testing.assert_allclose(np.asarray(full), 2 * np.asarray(x))


def test_random_ltd_scheduler():
    from deepspeed_tpu.runtime.data_pipeline import RandomLTDScheduler

    s = RandomLTDScheduler(seq_start=64, seq_full=256, total_steps=100,
                           step_size=16)
    assert s.update(0) == 64
    assert s.update(100) == 256
    assert 64 <= s.update(50) <= 256


# ---------------------------------------------------------------------------
# compression
# ---------------------------------------------------------------------------

def test_fake_quantize_error_bound(rng):
    from deepspeed_tpu.compression import fake_quantize

    w = jax.random.normal(rng, (32, 64))
    q = fake_quantize(w, bits=8)
    err = np.abs(np.asarray(q - w)).max()
    assert err <= float(jnp.abs(w).max()) / 127 + 1e-6


def test_layer_reduction_and_pruning(rng):
    from deepspeed_tpu.compression import (CompressedParams, magnitude_mask,
                                           reduce_layers)

    params = {"layers": {"w": jax.random.normal(rng, (4, 8, 8))},
              "embed": jnp.ones((10, 8))}
    red = reduce_layers(params, [0, 2])
    assert red["layers"]["w"].shape == (2, 8, 8)
    np.testing.assert_array_equal(np.asarray(red["layers"]["w"][1]),
                                  np.asarray(params["layers"]["w"][2]))
    m = magnitude_mask(params["layers"]["w"][0], density=0.25)
    assert 0.2 <= float(m.mean()) <= 0.3

    comp = CompressedParams({"compression_training": {
        "sparse_pruning": {"shared_parameters": {"enabled": True,
                                                 "dense_ratio": 0.5}}}})
    comp.init_masks(params)
    out = comp.apply(params)
    kept = float((np.asarray(out["layers"]["w"]) != 0).mean())
    assert 0.4 <= kept <= 0.6


def test_init_compression_api():
    from deepspeed_tpu.compression import init_compression, redundancy_clean

    model = SimpleModel(hidden_dim=8)
    model, comp = init_compression(model, {"compression_training": {
        "weight_quantization": {"shared_parameters": {"enabled": True}}}})
    assert comp.cfg.wq_enabled
    out = redundancy_clean(model, {}, params={"layers": {"w": jnp.ones((2, 4, 4))}})
    assert out["layers"]["w"].shape == (2, 4, 4)


# ---------------------------------------------------------------------------
# autotuning
# ---------------------------------------------------------------------------

def test_autotuner_picks_working_config():
    from deepspeed_tpu.autotuning import Autotuner

    x, y = random_dataset(n=32)

    def model_fn():
        return SimpleModel(hidden_dim=16), (x, y)

    tuner = Autotuner(model_fn,
                      base_config={"gradient_accumulation_steps": 1,
                                   "optimizer": {"type": "Adam",
                                                 "params": {"lr": 1e-2}}},
                      tuning_space={"zero_optimization.stage": [0, 1],
                                    "train_micro_batch_size_per_gpu": [1, 2]},
                      max_trials=4, steps_per_trial=2)
    best, results = tuner.tune()
    assert any(r["status"] == "ok" for r in results)
    assert "zero_optimization" in best


# ---------------------------------------------------------------------------
# GatheredParameters / zero.Init
# ---------------------------------------------------------------------------

def test_gathered_parameters_roundtrip():
    from deepspeed_tpu.runtime.zero import GatheredParameters, Init

    with Init():
        pass  # compatibility no-op

    x, y = random_dataset(n=16)
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 3}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, rng=jax.random.PRNGKey(0))
    engine.forward((x[:8], y[:8]))
    engine.step()
    old_shardings = jax.tree.map(lambda a: a.sharding, engine.state.params)
    with GatheredParameters(engine=engine) as full:
        for leaf in jax.tree_util.tree_leaves(full):
            leaf += 1.0  # modify-in-context (reference modifier contract)
    for leaf, sh in zip(jax.tree.leaves(engine.state.params),
                        jax.tree.leaves(old_shardings)):
        assert leaf.sharding == sh  # repartitioned identically
    # and the mutation took effect in the live engine state
    engine2_loss = engine.forward((x[:8], y[:8]))
    assert np.isfinite(float(engine2_loss))


# ---------------------------------------------------------------------------
# hybrid engine
# ---------------------------------------------------------------------------

def test_hybrid_engine_train_and_generate(mesh8, rng):
    from deepspeed_tpu.comm.mesh import set_global_mesh
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

    set_global_mesh(mesh8)
    model = causal_lm("llama-tiny", mesh=mesh8, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    cfg = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "steps_per_print": 10**9}
    engine = DeepSpeedHybridEngine(
        model=model, config=cfg, mesh=mesh8, rng=jax.random.PRNGKey(0),
        inference_config={"dtype": "float32", "max_out_tokens": 64})
    toks = jax.random.randint(rng, (8, 16), 0, 256)
    loss1 = engine.forward((toks, toks))
    engine.step()
    out1 = engine.generate(toks[:2, :8], max_new_tokens=4)
    assert out1.shape == (2, 12)
    # weights advance -> generation reflects the new params
    engine.forward((toks, toks))
    engine.step()
    out2 = engine.generate(toks[:2, :8], max_new_tokens=4)
    assert out2.shape == (2, 12)
    assert np.isfinite(float(loss1))


def test_engine_curriculum_integration(mesh8, rng):
    """ds_config curriculum section drives per-step seqlen truncation."""
    from deepspeed_tpu.comm.mesh import set_global_mesh
    from deepspeed_tpu.models import causal_lm

    set_global_mesh(mesh8)
    model = causal_lm("llama-tiny", mesh=mesh8, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    cfg = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "curriculum_learning": {"enabled": True,
                                   "curriculum_type": "fixed_linear",
                                   "min_difficulty": 16, "max_difficulty": 64,
                                   "schedule_config": {"total_curriculum_step": 4,
                                                       "difficulty_step": 16}},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               mesh=mesh8,
                                               rng=jax.random.PRNGKey(0))
    assert engine.curriculum_scheduler is not None
    toks = jax.random.randint(rng, (8, 64), 0, 256)
    engine.forward((toks, toks))
    engine.step()
    # step 1 of 4: raw 16 + 0.25*48 = 28, floored to the 16-step grid
    assert engine.curriculum_difficulty() == 16
    for _ in range(4):
        engine.forward((toks, toks))
        engine.step()
    assert engine.curriculum_difficulty() == 64  # ramp complete


def test_structured_pruning_masks(rng):
    """VERDICT r4 item 8: head/row/channel pruning on the stacked tree —
    pruned heads contribute exactly zero, pruned FFN units vanish from BOTH
    sides of the hidden dim."""
    from deepspeed_tpu.compression import head_pruning_masks, row_pruning_masks

    L, D, H, Dh, F = 2, 16, 4, 4, 32
    attn = {"wq": jax.random.normal(rng, (L, D, H * Dh)),
            "wo": jax.random.normal(jax.random.fold_in(rng, 1), (L, H * Dh, D))}
    am = head_pruning_masks(attn, num_heads=H, density=0.5)
    wo_m = np.asarray(attn["wo"] * am["wo"])
    kept_heads = (np.abs(wo_m.reshape(L, H, Dh, D)).sum((2, 3)) > 0).sum(1)
    assert (kept_heads == 2).all(), kept_heads          # exactly H/2 kept
    # the kept heads are the LARGEST by wo-norm
    norms = np.linalg.norm(np.asarray(attn["wo"]).reshape(L, H, -1), axis=-1)
    for l in range(L):
        kept = set(np.nonzero(np.abs(wo_m.reshape(L, H, Dh, D)[l]).sum((1, 2)))[0])
        assert kept == set(np.argsort(norms[l])[-2:])

    mlp = {"w_up": jax.random.normal(jax.random.fold_in(rng, 2), (L, D, F)),
           "w_gate": jax.random.normal(jax.random.fold_in(rng, 3), (L, D, F)),
           "w_down": jax.random.normal(jax.random.fold_in(rng, 4), (L, F, D)),
           "b_up": jax.random.normal(jax.random.fold_in(rng, 5), (L, F))}
    mm = row_pruning_masks(mlp, density=0.25)
    up_m = np.asarray(mlp["w_up"] * mm["w_up"])
    down_m = np.asarray(mlp["w_down"] * mm["w_down"])
    up_alive = np.abs(up_m).sum(1) > 0                   # [L, F]
    down_alive = np.abs(down_m).sum(2) > 0               # [L, F]
    np.testing.assert_array_equal(up_alive, down_alive)  # paired channels
    assert (up_alive.sum(1) == F // 4).all()


def test_compression_scheduler_engine_wired(rng):
    """The ENGINE consults the scheduler: pruning activates at
    schedule_offset mid-training with no global_step threading, and the
    optimizer cannot regrow pruned weights afterwards."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    model = causal_lm("llama-tiny", num_layers=2, vocab_size=128,
                      max_seq_len=64, remat=False)
    cfg = {"train_micro_batch_size_per_gpu": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "compression_training": {
               "row_pruning": {"shared_parameters": {"enabled": True,
                                                     "schedule_offset": 2},
                               "different_groups": {"rp1": {"params": {
                                   "dense_ratio": 0.5}}}},
               "head_pruning": {"shared_parameters": {"enabled": True,
                                                      "schedule_offset": 3,
                                                      "dense_ratio": 0.5}}},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               rng=jax.random.PRNGKey(0))
    assert engine._compression_sched is not None
    toks = jax.random.randint(rng, (8, 32), 0, 128)

    def dead_units():
        w_up = np.asarray(jax.device_get(
            engine.state.params["layers"]["mlp"]["w_up"]))
        return int((np.abs(w_up).sum(1) == 0).sum())

    def dead_heads():
        wo = np.asarray(jax.device_get(
            engine.state.params["layers"]["attn"]["wo"]))
        L, HDh, D = wo.shape
        H = model.config.num_heads
        return int((np.abs(wo.reshape(L, H, -1)).sum(-1) == 0).sum())

    step = lambda: (engine.backward(engine.forward((toks, toks))),
                    engine.step())
    step()
    assert dead_units() == 0 and dead_heads() == 0       # before offset
    step()
    F = model.config.intermediate_size
    assert dead_units() == 2 * (F - F // 2)              # row pruning live
    assert dead_heads() == 0                             # head offset not yet
    step()
    assert dead_heads() == 2 * (model.config.num_heads // 2)
    step()                                               # masks persist
    assert dead_units() == 2 * (F - F // 2)
    assert dead_heads() == 2 * (model.config.num_heads // 2)
