"""Serving-fleet replica supervisor (tools/serve_supervisor.py) and the
shared restart ladder (deepspeed_tpu/elasticity/supervisor.py): the
tier-1-wired selftest (real subprocess replicas driven through kill /
wedge / scale-out / scale-in / graceful shutdown), the fresh-interpreter
no-jax contract, and units for the shared RestartPolicy the train and
serve supervisors must not drift apart on."""

import os
import sys

from deepspeed_tpu.elasticity.supervisor import (PREEMPT_EXIT_CODE,
                                                 RestartPolicy)

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# the shared restart ladder (one source of truth for both supervisors)
# ---------------------------------------------------------------------------

def test_restart_policy_matches_train_supervisor_contract():
    """The exact PR 8 TrainSupervisor ladder: crash backoff doubles from
    backoff_base and caps at backoff_max, preempts restart free, the
    budget counts CRASHES only, and exhaustion gives up."""
    p = RestartPolicy(max_restarts=3, backoff_base=1.0, backoff_max=2.5)
    assert p.decide(0) == ("done", 0.0, "completed")
    a = p.decide(7)
    b = p.decide(7)
    c = p.decide(7)
    assert (a.action, a.delay) == ("restart", 1.0)
    assert (b.action, b.delay) == ("restart", 2.0)
    assert (c.action, c.delay) == ("restart", 2.5)      # capped
    assert p.backoffs == [1.0, 2.0, 2.5]
    d = p.decide(PREEMPT_EXIT_CODE)
    assert (d.action, d.delay, d.kind) == ("restart", 0.0, "preempt")
    assert p.crash_restarts == 3 and p.preempt_restarts == 1
    assert p.decide(7).action == "give_up"
    assert p.restarts == 4                               # give_up not counted


def test_restart_policy_healthy_reset_forgives_ladder():
    """The serve-supervisor long-horizon mode: a replica that ran past
    healthy_reset_s before crashing starts the ladder over — a
    once-a-day crash cannot exhaust a lifetime budget.  ran_s below the
    threshold keeps burning budget (crash loops still give up)."""
    p = RestartPolicy(max_restarts=2, backoff_base=1.0,
                      healthy_reset_s=60.0)
    assert p.decide(9, ran_s=1.0).delay == 1.0
    assert p.decide(9, ran_s=1.0).delay == 2.0
    assert p.decide(9, ran_s=1.0).action == "give_up"
    # a long healthy run resets the ladder: back to the first rung
    d = p.decide(9, ran_s=120.0)
    assert (d.action, d.delay) == ("restart", 1.0)
    assert p.crash_restarts == 1


def test_train_supervisor_exposes_shared_policy():
    """tools/train_supervisor.py rides the SHARED module (no private
    copy of the ladder left to drift): its counters are views of the
    policy's."""
    ts = _tool("train_supervisor")
    sup = ts.TrainSupervisor([sys.executable, "-c", "pass"],
                             max_restarts=2, backoff_base=0.5)
    assert isinstance(sup.policy, RestartPolicy)
    sup.policy.decide(7)
    assert sup.restarts == 1 and sup.crash_restarts == 1
    assert sup.backoffs == [0.5]


# ---------------------------------------------------------------------------
# the tool: selftest wired tier-1 + the no-jax contract
# ---------------------------------------------------------------------------

def test_serve_supervisor_tool_selftest():
    """tools/serve_supervisor.py --selftest drives the REAL supervisor
    over synthetic replica subprocesses: SIGKILL -> ladder restart,
    wedge (alive-but-unresponsive) -> SIGKILL + restart, sustained
    queue-depth scale-out, graceful drain scale-in, SIGTERM-fan-out
    shutdown."""
    tool = _tool("serve_supervisor")
    assert tool.main(["serve_supervisor", "--selftest"]) == 0


def test_serve_supervisor_runs_without_jax():
    """The fresh-interpreter RUNTIME half of the no-jax contract (the
    STATIC import-graph half is dslint DSL003, which now covers
    serve_supervisor.py in JAXFREE_TOOLS)."""
    import subprocess

    script = os.path.join(_TOOLS, "serve_supervisor.py")
    proc = subprocess.run(
        [sys.executable, script, "--selftest"], capture_output=True,
        text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert "serve_supervisor selftest: OK" in proc.stdout
