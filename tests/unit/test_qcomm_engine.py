"""comm_quantization call-site tests (ISSUE 15): every opted-in seam is
loss-parity-checked against its dense twin, the engine's quantized grad
all-reduce converges with the error-feedback residual carried as engine
state, the double byte ledger shows the ~2-4x wire reduction on ONE
trace, and the config hygiene contract (legacy ZeRO++ flags vs the
comm_quantization block, anomaly refuse-to-arm consistency) holds.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm import comm as comm_api
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.metrics import get_registry


def tiny_model(mesh, **over):
    kw = dict(num_layers=2, hidden_size=64, intermediate_size=128,
              num_heads=4, vocab_size=256, max_seq_len=64)
    kw.update(over)
    return causal_lm("gpt2-small", mesh=mesh, **kw)


def make_engine(mesh, stage=1, qcomm=None, extra=None, gas=2,
                model_over=None, lr=1e-3, opt="Adam"):
    model = tiny_model(mesh, **(model_over or {}))
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": opt, "params": {"lr": lr}},
           "gradient_clipping": 1.0,
           "zero_optimization": {"stage": stage},
           "steps_per_print": 10**9}
    if qcomm is not None:
        cfg["comm_quantization"] = qcomm
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, mesh=mesh, rng=jax.random.PRNGKey(7))
    return engine


def train(engine, steps=3, seed=0, fused=True, micro=16):
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        toks = jnp.asarray(rng.integers(0, 256, size=(micro, 32)),
                           jnp.int32)
        if fused:
            losses.append(float(engine.train_step((toks, toks))))
        else:
            gas = engine.config.gradient_accumulation_steps
            for i in range(gas):
                sl = toks[i * (micro // gas):(i + 1) * (micro // gas)]
                loss = engine.forward((sl, sl))
            engine.step()
            losses.append(float(loss))
    return losses


# ---------------------------------------------------------------------------
# engine grad all-reduce: parity + residual + bytes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stage", [0, 1, 2])
def test_qcomm_grad_loss_parity(devices, stage):
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    dense = train(make_engine(mesh, stage), seed=1)
    q_eng = make_engine(mesh, stage, qcomm={"grad_all_reduce": True})
    assert q_eng._qcomm_grads, q_eng._qcomm_grads_reason
    q = train(q_eng, seed=1)
    np.testing.assert_allclose(q, dense, rtol=0.05)
    # the residual is live engine state after a boundary
    assert q_eng._qcomm_residual is not None
    res_mag = sum(float(jnp.abs(r).sum())
                  for r in jax.tree.leaves(q_eng._qcomm_residual))
    assert res_mag > 0


def test_qcomm_grad_parity_without_error_feedback(devices):
    """ef off compiles the residual-free program variant (no full-model
    fp32 residual donated through every boundary — review finding) and
    still tracks dense closely at these scales."""
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    dense = train(make_engine(mesh, 1), seed=16)
    eng = make_engine(mesh, 1, qcomm={"grad_all_reduce": True,
                                      "error_feedback": False})
    q = train(eng, seed=16)
    np.testing.assert_allclose(q, dense, rtol=0.05)
    assert eng._qcomm_residual is None   # never allocated


def test_qcomm_grad_accum_loop_path(devices):
    """The non-fused forward/step path reduces through the same seam."""
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    dense = train(make_engine(mesh, 1), seed=2, fused=False)
    q = train(make_engine(mesh, 1, qcomm={"grad_all_reduce": True}),
              seed=2, fused=False)
    np.testing.assert_allclose(q, dense, rtol=0.05)


def test_qcomm_error_feedback_tracks_dense_trajectory(devices):
    """The convergence half of the error-feedback contract, end to end:
    with the residual carried across boundaries the compressed-grad loss
    trajectory matches the dense run step-for-step (the deterministic
    accumulation half — residual-off measurably worse — is pinned in
    test_collectives_q.test_error_feedback_bounds_accumulated_error)."""
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    toks = jnp.asarray(np.random.default_rng(3).integers(
        0, 256, size=(16, 32)), jnp.int32)

    def fixed_train(eng, steps=8):
        return [float(eng.train_step((toks, toks))) for _ in range(steps)]

    dense = fixed_train(make_engine(mesh, 1, lr=3e-3))
    ef = fixed_train(make_engine(mesh, 1, lr=3e-3,
                                 qcomm={"grad_all_reduce": True,
                                        "error_feedback": True}))
    np.testing.assert_allclose(ef, dense, atol=0.02)
    # both actually trained (fixed batch: the loss must fall)
    assert dense[-1] < dense[0] and ef[-1] < ef[0]


def test_qcomm_grad_bytes_2_to_4x_down_on_one_trace(devices):
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    reg = get_registry()
    reg.reset()
    comm_api.comms_logger.reset()
    eng = make_engine(mesh, 1, qcomm={"grad_all_reduce": True},
                      extra={"comms_logger": {"enabled": True}})
    train(eng, steps=2, seed=4)
    metrics = json.loads(reg.statz_json())["metrics"]

    def fam(name):
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return sum(x for x in v.values()
                       if isinstance(x, (int, float)))
        return v or 0

    wire = fam("ds_comm_q_all_reduce_bytes_total")
    dense = fam("ds_comm_q_all_reduce_dense_bytes_total")
    assert wire > 0 and dense > 0
    assert 2.0 <= dense / wire <= 4.5, (wire, dense)
    comm_api.comms_logger.configure(enabled=False)


def test_qcomm_residual_resets_on_checkpoint_load(devices, tmp_path):
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 1, qcomm={"grad_all_reduce": True})
    train(eng, steps=2, seed=5)
    assert eng._qcomm_residual is not None
    eng.save_checkpoint(str(tmp_path), tag="t1")
    eng.load_checkpoint(str(tmp_path), tag="t1")
    # transient sync state restarts at zero on resume (documented)
    assert eng._qcomm_residual is None
    losses = train(eng, steps=2, seed=6)
    assert np.isfinite(losses).all()
    assert eng._qcomm_residual is not None


# ---------------------------------------------------------------------------
# config hygiene + gating
# ---------------------------------------------------------------------------

def test_legacy_flag_contradiction_raises():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    with pytest.raises(ValueError, match="conflicting quantized-comm"):
        DeepSpeedConfig({"zero_optimization": {
            "stage": 3, "zero_quantized_weights": True},
            "comm_quantization": {"all_gather": False}},
            world_size=8)
    with pytest.raises(ValueError, match="conflicting quantized-comm"):
        DeepSpeedConfig({"zero_optimization": {
            "stage": 3, "zero_quantized_gradients": True},
            "comm_quantization": {"enabled": True,
                                  "reduce_scatter": False}},
            world_size=8)
    # agreeing settings compose; silence is not a vote
    cfg = DeepSpeedConfig({"zero_optimization": {
        "stage": 3, "zero_quantized_weights": True},
        "comm_quantization": {"all_gather": True}}, world_size=8)
    assert cfg.comm_quantization.q_all_gather
    cfg = DeepSpeedConfig({"comm_quantization": {"enabled": True}},
                          world_size=8)
    assert cfg.comm_quantization.q_grad_all_reduce
    assert cfg.comm_quantization.q_all_to_all


def test_qcomm_inert_configs_warn_loudly(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    # stage 3 has no boundary grad all-reduce: the knob must be loudly
    # inert, and training must run dense
    eng = make_engine(mesh, 3, qcomm={"grad_all_reduce": True})
    assert not eng._qcomm_grads
    assert any("comm_quantization.grad_all_reduce" in k
               for k in eng._inert_config_keys)
    # gather/scatter sites with neither overlap nor ZeRO++: inert too
    eng = make_engine(mesh, 1, qcomm={"all_gather": True})
    assert any("comm_quantization.all_gather" in k
               for k in eng._inert_config_keys)
    # ep>1 refuses the manual quantized-grad path (expert params shard
    # over ep — review finding: it used to crash at trace time)
    mesh_ep = build_mesh(dp=2, ep=4, devices=devices)
    set_global_mesh(mesh_ep)
    eng = make_engine(mesh_ep, 1, qcomm={"grad_all_reduce": True})
    assert not eng._qcomm_grads
    assert "ep" in (eng._qcomm_grads_reason or "")


def test_cq_sites_alone_activate_zeropp_at_stage3(devices):
    """Stage 3 without overlap: comm_quantization.all_gather/
    reduce_scatter activate the ZeRO++ path by themselves (review
    finding: the docstring promised it but want_zpp only read the
    legacy flags)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 3, qcomm={"all_gather": True,
                                      "reduce_scatter": True})
    assert eng._zeropp_active()
    losses = train(eng, steps=2, seed=17)
    assert eng._zpp_cfg.q_weights and eng._zpp_cfg.q_grads
    assert np.isfinite(losses).all()


def test_anomaly_refuse_to_arm_consistency(devices):
    """ZeRO++ keeps refusing to arm anomaly_detection when driven through
    comm_quantization-adjacent configs; the engine's qcomm grad path ARMS
    it (its apply carries the same in-program skip select as the standard
    path)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    zpp_eng = make_engine(
        mesh, 3,
        extra={"zero_optimization": {"stage": 3,
                                     "zero_quantized_weights": True,
                                     "zero_quantized_gradients": True},
               "anomaly_detection": {"enabled": True}})
    assert zpp_eng._zeropp_active()
    assert zpp_eng._anomaly is None          # refused, as documented
    mesh_dp = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh_dp)
    q_eng = make_engine(mesh_dp, 1, qcomm={"grad_all_reduce": True},
                        extra={"anomaly_detection": {"enabled": True}})
    assert q_eng._qcomm_grads and q_eng._anomaly is not None
    losses = train(q_eng, steps=2, seed=7)
    assert q_eng._anomaly_select
    assert np.isfinite(losses).all()


def test_anomaly_skip_rolls_back_residual(devices):
    """A skipped step must roll back the error-feedback residual WITH the
    params/opt state: the rejected gradients computed it, so carrying it
    would leak them into the next boundary — and a non-finite gradient
    would poison the carry permanently (review finding, pinned)."""
    mesh = build_mesh(dp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 1, qcomm={"grad_all_reduce": True},
                      extra={"anomaly_detection": {"enabled": True}},
                      gas=1)
    train(eng, steps=2, seed=13)          # populate a real residual
    before = jax.tree.map(np.asarray, eng._qcomm_residual)
    steps_before = int(eng.state.global_steps)
    rng = np.random.default_rng(14)
    toks = jnp.asarray(rng.integers(0, 256, size=(16, 32)), jnp.int32)
    eng.forward((toks, toks))             # fresh accumulated grads
    # drive the compiled apply with a bound every finite gnorm exceeds:
    # the in-program select must skip the step AND keep the residual
    st, gnorm, overflow = eng._apply_fn(eng.state, jnp.float32(1e-30))
    eng.state = st
    assert bool(overflow)
    assert int(eng.state.global_steps) == steps_before
    after = jax.tree.map(np.asarray, eng._qcomm_residual)
    jax.tree.map(np.testing.assert_array_equal, after, before)


def test_zeropp_through_comm_quantization_block(devices):
    """The stage-3 path accepts the legacy spellings and the shared-layer
    transport underneath records the q series + dense twins."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    comm_api.comms_logger.configure(enabled=True)
    comm_api.comms_logger.reset()
    eng = make_engine(
        mesh, 3,
        extra={"zero_optimization": {"stage": 3,
                                     "zero_quantized_weights": True,
                                     "zero_quantized_gradients": True}})
    losses = train(eng, steps=2, seed=8)
    counts = dict(comm_api.comms_logger.bytes)
    comm_api.comms_logger.configure(enabled=False)
    assert np.isfinite(losses).all()
    assert any("zpp_q_all_gather" in k for k in counts)
    assert any("q_reduce_scatter" in k for k in counts)


def test_comm_quantization_drives_zeropp_without_legacy_flags(devices):
    """Either spelling alone activates the seam: an hpz-armed ZeRO++
    engine with ONLY the comm_quantization block must run quantized
    transport (review regression: it silently ran dense)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(
        mesh, 3, qcomm={"enabled": True},
        extra={"zero_optimization": {"stage": 3,
                                     "zero_hpz_partition_size": 2}})
    losses = train(eng, steps=2, seed=15)
    assert eng._zeropp_active()
    assert eng._zpp_cfg.q_weights and eng._zpp_cfg.q_grads
    assert np.isfinite(losses).all()


# ---------------------------------------------------------------------------
# overlap schedule call site
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stage", [2, 3])
def test_overlap_quantized_loss_parity_and_plan(devices, stage):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)

    def mk(q):
        extra = {"zero_optimization": {
            "stage": stage, "overlap_comm": True,
            "overlap_bucket_layers": 1,
            "stage3_param_persistence_threshold": 0}}
        qc = ({"all_gather": True, "reduce_scatter": True} if q else None)
        eng = make_engine(mesh, stage, qcomm=qc, extra=extra,
                          model_over={"num_layers": 2})
        toks = jnp.zeros((16, 32), jnp.int32)
        eng.lazy_init_from_batch((toks, toks))
        assert eng._overlap, eng._overlap_reason
        return eng

    dense = train(mk(False), steps=3, seed=9)
    q_eng = mk(True)
    q = train(q_eng, steps=3, seed=9)
    np.testing.assert_allclose(q, dense, rtol=0.05)
    plan = q_eng._comm_plan
    ops = {e[0] for e in plan["micro"]}
    if stage == 3:
        assert "q_all_gather" in ops
    assert "q_reduce_scatter" in ops
    for e in plan["micro"]:
        if e[0].startswith("q_"):
            # 6-tuple: wire bytes + the (dense twin, dense dtype) pair,
            # ~2-4x apart
            assert len(e) == 6
            dense_bytes, dense_dtype = e[5]
            assert dense_dtype in ("float32", "bfloat16")
            assert 2.0 <= dense_bytes / e[2] <= 4.5, e
    # the device-capture byte ledger must digest 6-tuple entries too
    # (review regression: a 5-field unpack died exactly here)
    per_op = q_eng._profile_bytes_per_op(2)
    assert per_op and "q_reduce_scatter" in per_op


# ---------------------------------------------------------------------------
# MoE dispatch + sequence ring + all_to_all_single call sites
# ---------------------------------------------------------------------------

def test_moe_q_dispatch_loss_parity(devices):
    mesh = build_mesh(dp=2, ep=4, devices=devices)
    set_global_mesh(mesh)

    def mk(q):
        model = causal_lm("mixtral-tiny", mesh=mesh, num_layers=2,
                          hidden_size=64, intermediate_size=128,
                          num_heads=4, vocab_size=256, max_seq_len=64,
                          num_experts=4)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 0},
               "steps_per_print": 10**9}
        if q:
            cfg["comm_quantization"] = {"all_to_all": True}
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=cfg, mesh=mesh,
            rng=jax.random.PRNGKey(7))
        if q:
            assert eng.module.config.moe_q_dispatch
        return eng

    dense = train(mk(False), steps=3, seed=10, micro=8)
    reg = get_registry()
    reg.reset()
    comm_api.comms_logger.reset()
    comm_api.comms_logger.configure(enabled=True)
    reg.enable()
    try:
        q = train(mk(True), steps=3, seed=10, micro=8)
    finally:
        comm_api.comms_logger.configure(enabled=False)
    np.testing.assert_allclose(q, dense, rtol=0.08)
    # the dispatch/combine boundary records wire + dense-twin bytes on
    # one trace, ~2-4x apart (fp32 activations on the CPU mesh)
    metrics = json.loads(reg.statz_json())["metrics"]

    def fam(name):
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return sum(x for x in v.values()
                       if isinstance(x, (int, float)))
        return v or 0

    wire = fam("ds_comm_q_all_to_all_bytes_total")
    dense_eq = fam("ds_comm_q_all_to_all_dense_bytes_total")
    assert wire > 0 and 2.0 <= dense_eq / wire <= 4.5, (wire, dense_eq)


def test_ring_quantized_parity_and_grads(devices):
    from deepspeed_tpu.sequence.layer import ring_attention

    mesh = build_mesh(sp=8, devices=devices)
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (2, 4, 64, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 64, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 64, 16))
    dense = ring_attention(q, k, v, mesh, causal=True)
    quant = ring_attention(q, k, v, mesh, causal=True, quantized=True)
    assert np.abs(np.asarray(quant) - np.asarray(dense)).max() < 0.05

    def loss_fn(q_, k_, v_, use_q):
        return jnp.sum(ring_attention(q_, k_, v_, mesh, causal=True,
                                      quantized=use_q) ** 2)

    gd = jax.grad(loss_fn, argnums=(0, 1, 2))(q, k, v, False)
    gq = jax.grad(loss_fn, argnums=(0, 1, 2))(q, k, v, True)
    for a, b in zip(gq, gd):
        rel = (np.abs(np.asarray(a) - np.asarray(b)).max()
               / (np.abs(np.asarray(b)).max() + 1e-9))
        assert rel < 0.15, rel
    # the ring hop's wire/dense-twin ratio on one trace (codes vs the
    # fp32 chunk each q_ppermute replaced)
    reg = get_registry()
    reg.reset()
    comm_api.comms_logger.reset()
    comm_api.comms_logger.configure(enabled=True)
    reg.enable()
    try:
        jax.eval_shape(lambda a, b, c: ring_attention(
            a, b, c, mesh, causal=True, quantized=True), q, k, v)
    finally:
        comm_api.comms_logger.configure(enabled=False)
    metrics = json.loads(reg.statz_json())["metrics"]

    def fam(name):
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return sum(x for x in v.values()
                       if isinstance(x, (int, float)))
        return v or 0

    wire = fam("ds_comm_q_ppermute_bytes_total")
    dense_eq = fam("ds_comm_q_ppermute_dense_bytes_total")
    assert wire > 0 and 2.0 <= dense_eq / wire <= 4.5, (wire, dense_eq)


def test_seq_ring_q_wired_through_model_config(devices):
    mesh = build_mesh(sp=2, dp=4, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 0, qcomm={"sequence_ring": True}, gas=1,
                      model_over={"sp_mode": "ring"})
    assert eng.module.config.seq_ring_q
    losses = train(eng, steps=2, seed=11, micro=8)
    assert np.isfinite(losses).all()


def test_all_to_all_single_quantized_opt_in(devices, rng):
    import functools

    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(dp=8, devices=devices)
    x = jax.random.normal(rng, (64, 64))

    def body(xl):
        d = comm_api.all_to_all_single(xl, "dp")
        qv = comm_api.all_to_all_single(xl, "dp", quantized=True)
        return d, qv

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("dp"),
                              out_specs=(P("dp"), P("dp")),
                              check_vma=False))
    d, qv = f(x)
    np.testing.assert_allclose(
        np.asarray(qv), np.asarray(d),
        atol=float(np.abs(np.asarray(x)).max()) / 127 + 1e-5)


# ---------------------------------------------------------------------------
# streamed embed/head aux transport (offload satellite)
# ---------------------------------------------------------------------------

def test_streamer_aux_transport_quantizes_embed_head(devices):
    """The PR 10 'embed/head stay bf16' gap: put_aux ships int8 codes +
    scales (fewer relay bytes than the dense tree), materialize_aux
    round-trips within quantization error, and one source binding
    quantizes once."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.monitor.metrics import MetricsRegistry
    from deepspeed_tpu.runtime.zero.streaming import ParamStreamer

    mesh = build_mesh(dp=8, devices=devices)
    sh = {"tok": NamedSharding(mesh, P()), "pos": NamedSharding(mesh, P())}
    reg = MetricsRegistry().enable()
    streamer = ParamStreamer(sh, int8=True, registry=reg)
    rng = np.random.default_rng(0)
    tree = {"tok": np.asarray(rng.normal(size=(256, 64)), np.float32),
            "pos": np.asarray(rng.normal(size=(64, 64)), np.float32)}
    payload = streamer.put_aux("embed", tree, sh, src_key=1)
    assert set(payload) == {"q", "scale"}
    for leaf in jax.tree.leaves(payload["q"]):
        assert leaf.dtype == jnp.int8
    back = jax.jit(lambda p: streamer.materialize_aux("embed", p))(payload)
    for key in tree:
        tol = np.abs(tree[key]).max() / 127 + 1e-6
        np.testing.assert_allclose(np.asarray(back[key]), tree[key],
                                   atol=tol)
    # relay ledger: int8 payload ~4x under the dense fp32 tree
    dense_bytes = sum(a.nbytes for a in tree.values())
    snap = json.loads(reg.statz_json())["metrics"]
    fam = snap.get("ds_offload_relay_bytes_total", {})
    h2d = fam.get('{dir="h2d"}', 0) if isinstance(fam, dict) else fam
    assert 0 < h2d < 0.35 * dense_bytes, (h2d, dense_bytes)
    # same src_key -> cached quantization object
    qt1 = streamer._aux_q["embed"][1]
    streamer.put_aux("embed", tree, sh, src_key=1)
    assert streamer._aux_q["embed"][1] is qt1
    # new src_key -> requantize
    streamer.put_aux("embed", tree, sh, src_key=2)
    assert streamer._aux_q["embed"][1] is not qt1


def test_streamed_offload_int8_embed_head_loss_parity(devices):
    """End to end: the streamed-offload engine with int8_stream now ships
    embed/head quantized too, and stays loss-close to the dense-relay
    engine (the existing layer-stream parity contract, extended)."""
    mesh = build_mesh(dp=1, devices=devices[:1])
    set_global_mesh(mesh)

    def mk(int8):
        model = tiny_model(mesh)
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {
                   "stage": 2,
                   "offload_optimizer": {"device": "cpu"},
                   "offload_param": {"device": "cpu",
                                     "int8_stream": int8}},
               "steps_per_print": 10**9}
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=cfg, mesh=mesh,
            rng=jax.random.PRNGKey(7))
        return eng

    rng = np.random.default_rng(12)
    toks = jnp.asarray(rng.integers(0, 256, size=(4, 32)), jnp.int32)

    def run(eng):
        out = []
        for _ in range(3):
            loss = eng.forward((toks, toks))
            eng.step()
            out.append(float(loss))
        return out

    dense = run(mk(False))
    q = run(mk(True))
    np.testing.assert_allclose(q, dense, rtol=5e-2)
    assert np.isfinite(q).all()


# ---------------------------------------------------------------------------
# pipeline boundary site (ISSUE 16): tri-state config, fp16 refusal,
# engine wiring + analytic comm plan + metrics_dump compression column
# ---------------------------------------------------------------------------

def test_pipeline_site_tristate_and_fp16_refusal():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    # tri-state: None follows `enabled`, explicit value wins
    cfg = DeepSpeedConfig({"comm_quantization": {"enabled": True}},
                          world_size=8)
    assert cfg.comm_quantization.q_pipeline
    cfg = DeepSpeedConfig({"comm_quantization": {"enabled": True,
                                                 "pipeline": False}},
                          world_size=8)
    assert not cfg.comm_quantization.q_pipeline
    cfg = DeepSpeedConfig({"comm_quantization": {"pipeline": True}},
                          world_size=8)
    assert cfg.comm_quantization.q_pipeline
    cfg = DeepSpeedConfig({"comm_quantization": {}}, world_size=8)
    assert not cfg.comm_quantization.q_pipeline

    # fp16 loss scaling + int8 boundary: refuse to arm — saturation maps
    # inf/nan cotangents onto finite codes, blinding the overflow detector
    with pytest.raises(ValueError, match="pipeline cannot arm under fp16"):
        DeepSpeedConfig({"fp16": {"enabled": True},
                         "comm_quantization": {"pipeline": True}},
                        world_size=8)
    # ... including via the blanket `enabled` default
    with pytest.raises(ValueError, match="pipeline cannot arm under fp16"):
        DeepSpeedConfig({"fp16": {"enabled": True},
                         "comm_quantization": {"enabled": True}},
                        world_size=8)
    # the documented escape hatch: pin the pipeline site dense
    cfg = DeepSpeedConfig({"fp16": {"enabled": True},
                           "comm_quantization": {"enabled": True,
                                                 "pipeline": False}},
                          world_size=8)
    assert not cfg.comm_quantization.q_pipeline
    assert cfg.comm_quantization.q_grad_all_reduce


def test_pp_boundary_q_wired_and_comm_plan(devices, tmp_path):
    """comm_quantization.pipeline=true on a pp mesh arms the model flag,
    hands the byte ledger to the engine (pp_comm_record=False — feed
    disjointness), lands an analytic q_ppermute plan entry with a >=2x
    dense twin, and the committed series reach `metrics_dump --comms`
    with the compression column populated."""
    import os
    import sys

    mesh = build_mesh(pp=2, fsdp=4, devices=devices)
    set_global_mesh(mesh)
    reg = get_registry()
    reg.reset()
    comm_api.comms_logger.reset()
    eng = make_engine(mesh, 1, qcomm={"pipeline": True},
                      extra={"comms_logger": {"enabled": True}})
    mcfg = eng.module.config
    assert mcfg.pp_boundary_q is True
    assert mcfg.pp_comm_record is False
    losses = train(eng, steps=2, seed=2)
    assert np.isfinite(losses).all()

    q_entries = [e for e in eng._comm_plan["micro"]
                 if e[0] == "q_ppermute"]
    assert q_entries, eng._comm_plan
    (_, hops, wire, dtype, world, dense_twin) = q_entries[0]
    dense_bytes, dense_dtype = dense_twin
    assert dtype == "int8" and world == 2 and hops > 0
    assert dense_bytes / wire >= 2.0, (wire, dense_bytes)

    # committed ledger -> statz snapshot -> the comms table
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    snap = tmp_path / "statz.json"
    snap.write_text(reg.statz_json())
    rows = metrics_dump.comms_rows(metrics_dump.load_snapshot(str(snap)))
    by_op = {r[0]: r for r in rows}
    assert "q_ppermute" in by_op, sorted(by_op)
    compress = by_op["q_ppermute"][3]
    assert compress.endswith("x") and float(compress[:-1]) >= 2.0, compress
    comm_api.comms_logger.configure(enabled=False)


def test_pipeline_site_inert_without_pp(devices):
    """pipeline=true with no pp mesh axis: loudly inert (audit key), and
    the model flag stays dense — nothing quantizes."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    eng = make_engine(mesh, 1, qcomm={"pipeline": True})
    assert any("comm_quantization.pipeline" in k
               for k in eng._inert_config_keys)
    assert eng.module.config.pp_boundary_q is False
