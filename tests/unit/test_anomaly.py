"""bf16/fp32 anomaly containment (docs/RESILIENCE.md "Elastic training"):
the ``anomaly_detection`` skip -> rollback ladder.

A gradient bomb (``testing/chaos.gradient_bomb``) must be CONTAINED: the
anomalous step is skipped in-program (the fp16 ``has_overflow`` select,
mirrored — params/opt state untouched, global_steps not advanced), and
after ``patience`` consecutive trips the engine dumps the flight recorder
and rolls back to the last-good checkpoint, after which the run
re-converges loss-identical to a run that never saw the bomb.
"""

import math
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.monitor.anomaly import GradAnomalyDetector
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.testing import chaos
from tests.unit.simple_model import SimpleModel, random_dataset

X, Y = random_dataset(n=32)


# ---------------------------------------------------------------------------
# detector units (jax-free)
# ---------------------------------------------------------------------------


def test_detector_warmup_never_trips_on_spikes():
    d = GradAnomalyDetector(factor=5.0, window=8, warmup=4)
    assert d.bound == math.inf
    for g in (1.0, 100.0, 1.2):         # wild swings during warmup: accepted
        assert not d.observe(g)
    assert d.bound == math.inf
    assert not d.observe(1.1)           # 4th sample arms the bound
    assert d.bound < math.inf


def test_detector_nonfinite_trips_even_unarmed():
    d = GradAnomalyDetector(factor=5.0, window=8, warmup=4)
    assert d.observe(float("nan"))
    assert d.observe(float("inf"))
    assert d.last_trip["kind"] == "non_finite"
    assert d.consecutive == 2 and d.trips_total == 2
    assert not d.observe(1.0)           # healthy sample resets the run
    assert d.consecutive == 0


def test_detector_spike_vs_drift_and_cached_bound():
    d = GradAnomalyDetector(factor=4.0, window=16, warmup=4, patience=2)
    for _ in range(6):
        assert not d.observe(1.0)
    rec0 = d.median_recomputes
    assert not d.observe(1.01)          # under the cached bound: fast path
    assert d.median_recomputes == rec0
    # a genuine spike trips and NEVER enters the window
    assert d.observe(50.0)
    assert d.last_trip["kind"] == "spike"
    assert abs(d.median - 1.0) < 0.02
    # slow drift above the cached bound but under factor x median is a
    # false alarm: accepted, and the bound refreshes so the new normal
    # stops taking the slow path
    assert not d.observe(3.9)
    assert d.consecutive == 0
    # escalation: patience consecutive trips -> should_rollback
    assert d.observe(50.0) and not d.should_rollback
    assert d.observe(50.0) and d.should_rollback
    d.note_rollback()
    assert d.consecutive == 0 and d.rollbacks == 1 and d.rollback_streak == 1
    # an accepted step forgives the rollback streak (not the lifetime count)
    assert not d.observe(1.0)
    assert d.rollback_streak == 0 and d.rollbacks == 1


def test_detector_bound_reanchors_as_median_falls():
    d = GradAnomalyDetector(factor=5.0, window=4, warmup=2)
    for g in (100.0, 100.0):            # compile-era noise inflates warmup
        d.observe(g)
    high = d.bound
    for g in (1.0, 1.0, 1.0, 1.0, 1.0):  # training settles
        assert not d.observe(g)
    assert d.bound < high               # once-per-window re-anchor
    assert d.observe(30.0)              # a spike vs the NEW median trips


# ---------------------------------------------------------------------------
# engine e2e
# ---------------------------------------------------------------------------


def _make_engine(tmp_path, stage=0, masters=None, patience=2, rollback=True,
                 max_rollbacks=3):
    zero = {"stage": stage}
    if masters is not None:
        zero["offload_optimizer"] = {"device": "cpu",
                                     "int8_masters": masters == "int8"}
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": zero, "steps_per_print": 10**9,
           "anomaly_detection": {"enabled": True, "factor": 5.0,
                                 "window": 8, "warmup": 3,
                                 "patience": patience, "rollback": rollback,
                                 "max_rollbacks": max_rollbacks,
                                 "save_dir": str(tmp_path)},
           "flight_recorder": {"enabled": True, "dump_dir": str(tmp_path)}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg,
        rng=jax.random.PRNGKey(3))
    return engine


def _step(engine, i):
    lo = (i % 4) * 8
    loss = engine.forward((X[lo:lo + 8], Y[lo:lo + 8]))
    engine.step()
    return float(loss)


def _params(engine):
    return jax.tree.map(lambda a: np.array(a),
                        jax.device_get(engine.state.params))


@pytest.mark.parametrize("masters", [None, "fp32", "int8"])
def test_gradient_bomb_contained_skip_then_rollback(tmp_path, masters):
    """THE containment e2e, on the in-program select path (plain state)
    and both host-master offload paths: 3 bombed steps -> every one
    skipped (params frozen), 2 consecutive detections -> flight dump +
    rollback to the last-good tag, then the run re-converges
    loss-identical to a run that never saw the bomb."""
    reg = get_registry()
    reg.enable()
    flight = get_flight_recorder()
    flight.reset()
    try:
        # clean first: the process-global flight recorder keeps the LAST
        # enable()'s dump_dir, which must be tmp_path for the dump assert
        clean = _make_engine(tmp_path / "clean", masters=masters)
        engine = _make_engine(tmp_path, masters=masters)
        for i in range(5):
            _step(engine, i)
            _step(clean, i)
        engine.save_checkpoint(str(tmp_path), tag="good")
        good = _params(engine)
        steps0 = engine.global_steps
        sk0 = reg.counter("ds_train_anomaly_skipped_total").value
        rb0 = reg.counter("ds_train_anomaly_rollback_total").value

        with chaos.gradient_bomb(engine, scale=1e18, on_call=1, n=3) as st:
            for i in range(3):
                _step(engine, 5 + i)
        assert st["bombed"] == 3
        # every bombed step was a no-op on the params (skip select /
        # host-side skip), and the rollback restored the good tag
        for a, b in zip(jax.tree.leaves(good),
                        jax.tree.leaves(_params(engine))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert engine.global_steps == steps0
        assert reg.counter("ds_train_anomaly_skipped_total").value \
            - sk0 >= 2
        assert reg.counter("ds_train_anomaly_rollback_total").value \
            - rb0 == 1
        kinds = [e["kind"] for e in flight.events()]
        assert "anomaly_skip" in kinds and "anomaly_rollback" in kinds
        assert os.path.exists(str(tmp_path)) and any(
            n.startswith("ds_flight") for n in os.listdir(tmp_path))

        # post-rollback: loss-identical to the engine that never bombed
        after = [_step(engine, 5 + i) for i in range(4)]
        ref = [_step(clean, 5 + i) for i in range(4)]
        assert after == ref, (after, ref)
        assert engine._anomaly.consecutive == 0
    finally:
        flight.disable()
        reg.disable()


def test_spike_skip_without_rollback(tmp_path):
    """A single finite spike (below patience) skips exactly one step and
    never rolls back; the next healthy step trains normally."""
    reg = get_registry()
    reg.enable()
    try:
        engine = _make_engine(tmp_path, patience=3)
        for i in range(5):
            _step(engine, i)
        engine.save_checkpoint(str(tmp_path), tag="good")
        p0 = _params(engine)
        rb0 = reg.counter("ds_train_anomaly_rollback_total").value
        with chaos.gradient_bomb(engine, scale=1e3, on_call=1, n=1):
            _step(engine, 5)
        # the spike step froze params...
        for a, b in zip(jax.tree.leaves(p0),
                        jax.tree.leaves(_params(engine))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _step(engine, 6)                 # lag-1 tick classifies the spike
        assert engine._anomaly.trips_total >= 1
        assert reg.counter("ds_train_anomaly_rollback_total").value == rb0
        # ...and the healthy step after it moved them
        assert any(not np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(jax.tree.leaves(p0),
                                   jax.tree.leaves(_params(engine))))
    finally:
        reg.disable()


def test_persistent_anomaly_exhausts_max_rollbacks(tmp_path):
    """A bomb that persists across restores must not loop forever: after
    ``max_rollbacks`` ladder rollbacks with no accepted step in between,
    the engine raises."""
    engine = _make_engine(tmp_path, patience=1, max_rollbacks=1)
    for i in range(5):
        _step(engine, i)
    engine.save_checkpoint(str(tmp_path), tag="good")
    with pytest.raises(RuntimeError, match="max_rollbacks"):
        with chaos.gradient_bomb(engine, scale=1e18, on_call=1, n=10):
            for i in range(10):
                _step(engine, 5 + i)


def test_rollback_without_savedir_degrades_to_skips(tmp_path):
    """No checkpoint to restore: the ladder logs, re-arms, and the run
    keeps skipping instead of crashing."""
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10**9,
           "anomaly_detection": {"enabled": True, "factor": 5.0,
                                 "window": 8, "warmup": 3, "patience": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg,
        rng=jax.random.PRNGKey(3))
    for i in range(4):
        _step(engine, i)
    p0 = _params(engine)
    with chaos.gradient_bomb(engine, scale=1e18, on_call=1, n=5):
        for i in range(5):
            _step(engine, 4 + i)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(_params(engine))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert engine._anomaly.rollbacks == 0


def test_disabled_by_default_and_fused_path_skips(tmp_path):
    """Default engines carry no detector (the step program is the
    historical one-arg form); with the detector on, the FUSED
    single-dispatch train_step also skips in-program."""
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}, "steps_per_print": 10**9}
    plain, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg,
        rng=jax.random.PRNGKey(3))
    assert plain._anomaly is None and not plain._anomaly_select

    cfg = dict(cfg)
    cfg["anomaly_detection"] = {"enabled": True, "factor": 5.0,
                                "window": 8, "warmup": 2, "patience": 99}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg,
        rng=jax.random.PRNGKey(3))
    batch = (X[:32].reshape(2, 16, -1), Y[:32].reshape(2, 16, -1))
    for _ in range(4):
        engine.train_batch(iter([(X[:16], Y[:16]), (X[16:32], Y[16:32])]))
    assert engine._anomaly_select
    p0 = _params(engine)
    steps0 = engine.global_steps
    bombed = (X[:32].reshape(2, 16, -1) * 1e18,
              Y[:32].reshape(2, 16, -1))
    engine.train_step(bombed)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(_params(engine))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert engine.global_steps == steps0
    engine.train_step(batch)             # healthy fused step trains
    assert engine.global_steps == steps0 + 1
