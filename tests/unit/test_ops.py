"""Pallas kernel parity tests: every kernel in interpret mode vs the jnp
reference (SURVEY.md §4 implication (b)), plus gradient checks via custom VJP.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas import (apply_rotary_pos_emb, bias_act, flash_attention,
                                      fused_adam_update, layer_norm, mha_reference,
                                      rms_norm, rope_angles, scaled_masked_softmax)


def rand(*shape, dtype=jnp.float32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=dtype)


class TestLayerNorm:
    @pytest.mark.parametrize("shape", [(4, 128), (2, 8, 256)])
    def test_forward_parity(self, shape):
        x = rand(*shape)
        g = rand(shape[-1], seed=1) * 0.1 + 1.0
        b = rand(shape[-1], seed=2) * 0.1
        ref = layer_norm(x, g, b, 1e-5, "xla")
        out = layer_norm(x, g, b, 1e-5, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_backward_parity(self):
        x = rand(8, 128)
        g = rand(128, seed=1) * 0.1 + 1.0
        b = rand(128, seed=2) * 0.1

        def loss(impl):
            def f(x, g, b):
                return jnp.sum(layer_norm(x, g, b, 1e-5, impl) ** 2)
            return jax.grad(f, argnums=(0, 1, 2))(x, g, b)

        for got, ref in zip(loss("interpret"), loss("xla")):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)

    def test_bf16_io(self):
        x = rand(8, 128).astype(jnp.bfloat16)
        g = jnp.ones(128, jnp.bfloat16)
        b = jnp.zeros(128, jnp.bfloat16)
        out = layer_norm(x, g, b, 1e-5, "interpret")
        assert out.dtype == jnp.bfloat16


class TestRMSNorm:
    def test_forward_parity(self):
        x = rand(6, 256)
        g = rand(256, seed=3) * 0.1 + 1.0
        ref = rms_norm(x, g, 1e-6, "xla")
        out = rms_norm(x, g, 1e-6, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_backward_parity(self):
        x = rand(4, 128)
        g = rand(128, seed=1) * 0.1 + 1.0

        def grads(impl):
            def f(x, g):
                return jnp.sum(jnp.sin(rms_norm(x, g, 1e-6, impl)))
            return jax.grad(f, argnums=(0, 1))(x, g)

        for got, ref in zip(grads("interpret"), grads("xla")):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-4)


class TestRoPE:
    def test_forward_parity(self):
        B, H, S, D = 2, 4, 16, 64
        x = rand(B, H, S, D)
        cos, sin = rope_angles(jnp.arange(S), D)
        ref = apply_rotary_pos_emb(x, cos, sin, "xla")
        out = apply_rotary_pos_emb(x, cos, sin, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_norm_preserved(self):
        x = rand(1, 2, 8, 32)
        cos, sin = rope_angles(jnp.arange(8), 32)
        y = apply_rotary_pos_emb(x, cos, sin, "xla")
        # rotation preserves per-pair norms
        np.testing.assert_allclose(np.linalg.norm(np.asarray(x)), np.linalg.norm(np.asarray(y)),
                                   rtol=1e-5)

    def test_backward_is_inverse_rotation(self):
        x = rand(1, 1, 8, 16)
        cos, sin = rope_angles(jnp.arange(8), 16)

        def f(x):
            return jnp.sum(apply_rotary_pos_emb(x, cos, sin, "xla") * 2.0)

        gx = jax.grad(f)(x)
        expected = apply_rotary_pos_emb(jnp.full_like(x, 2.0), cos, -sin, "xla")
        np.testing.assert_allclose(np.asarray(gx), np.asarray(expected), rtol=1e-5, atol=1e-6)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("S", [128, 256])
    def test_forward_parity(self, causal, S):
        B, H, D = 1, 2, 64
        q, k, v = (rand(B, H, S, D, seed=i) for i in range(3))
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal, None, 64, 64, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_parity(self, causal):
        B, H, S, D = 1, 1, 128, 32
        q, k, v = (rand(B, H, S, D, seed=i + 10) for i in range(3))

        def loss_pallas(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, None, 64, 64, "interpret") ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        got = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(got, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=5e-3, atol=5e-4)

    def test_causal_masks_future(self):
        B, H, S, D = 1, 1, 64, 32
        q, k, v = (rand(B, H, S, D, seed=i) for i in range(3))
        out1 = flash_attention(q, k, v, True, None, 32, 32, "interpret")
        # changing future K/V must not affect past outputs
        k2 = k.at[:, :, S // 2:, :].set(0.0)
        v2 = v.at[:, :, S // 2:, :].set(0.0)
        out2 = flash_attention(q, k2, v2, True, None, 32, 32, "interpret")
        np.testing.assert_allclose(np.asarray(out1[:, :, :S // 2]),
                                   np.asarray(out2[:, :, :S // 2]), rtol=1e-5, atol=1e-6)

    def test_alibi_forward_parity(self):
        """ALiBi in-kernel bias == jnp reference with the explicit bias
        tensor (VERDICT r4 item 3: alibi in the flash kernels)."""
        from deepspeed_tpu.models.layers import alibi_bias

        B, H, S, D = 2, 6, 128, 32   # 6 heads: non-power-of-2 slope path
        q, k, v = (rand(B, H, S, D, seed=i) for i in range(3))
        pos = jnp.arange(S)
        bias = alibi_bias(H, pos, pos)[None]
        ref = mha_reference(q, k, v, causal=True, bias=bias)
        out = flash_attention(q, k, v, True, None, 64, 64, "interpret", True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_alibi_backward_parity(self):
        from deepspeed_tpu.models.layers import alibi_bias

        B, H, S, D = 1, 4, 128, 32
        q, k, v = (rand(B, H, S, D, seed=i + 20) for i in range(3))
        pos = jnp.arange(S)
        bias = alibi_bias(H, pos, pos)[None]

        def loss_pallas(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, None, 64, 64,
                                           "interpret", True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True, bias=bias) ** 2)

        got = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(got, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-3, atol=5e-4)


class TestSoftmax:
    def test_parity_with_mask(self):
        x = rand(4, 8, 128)
        mask = (rand(4, 8, 128, seed=5) > 0).astype(jnp.int32)
        ref = scaled_masked_softmax(x, mask, 0.5, "xla")
        out = scaled_masked_softmax(x, mask, 0.5, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)

    def test_no_mask(self):
        x = rand(16, 64)
        ref = scaled_masked_softmax(x, None, 1.0, "xla")
        out = scaled_masked_softmax(x, None, 1.0, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


class TestBiasAct:
    @pytest.mark.parametrize("act", ["gelu", "relu", "silu"])
    def test_parity(self, act):
        x = rand(8, 256)
        b = rand(256, seed=9)
        ref = bias_act(x, b, act, "xla")
        out = bias_act(x, b, act, "interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


class TestFusedAdam:
    def test_parity_with_optax(self):
        p = rand(257, 33)  # odd size exercises padding
        g = rand(257, 33, seed=1)
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        import optax

        tx = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
        st = tx.init(p)
        upd, _ = tx.update(g, st, p)
        ref = optax.apply_updates(p, upd)

        pn, mn, vn = fused_adam_update(p, g, m, v, jnp.asarray(1), lr=1e-2,
                                       weight_decay=0.01, adam_w_mode=True, impl="interpret")
        np.testing.assert_allclose(np.asarray(pn), np.asarray(ref), rtol=1e-5, atol=1e-6)

    def test_xla_equals_interpret(self):
        p = rand(100)
        g = rand(100, seed=2)
        m = jnp.zeros_like(p); v = jnp.zeros_like(p)
        a = fused_adam_update(p, g, m, v, jnp.asarray(3), lr=1e-3, impl="xla")
        b = fused_adam_update(p, g, m, v, jnp.asarray(3), lr=1e-3, impl="interpret")
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7)

    def test_engine_uses_fused_adam(self, monkeypatch):
        """FusedAdam type in ds_config routes to the Pallas update kernel
        (not a silent optax.adamw fallback) and trains via the engine."""
        import deepspeed_tpu
        import deepspeed_tpu.ops.adam.fused_adam as fa_mod
        from deepspeed_tpu.ops.adam.fused_adam import FusedAdamState
        from tests.unit.simple_model import SimpleModel, random_dataset

        calls = []
        real = fa_mod.fused_adam_update

        def spy(*args, **kwargs):
            calls.append(1)
            return real(*args, **kwargs)

        monkeypatch.setattr(fa_mod, "fused_adam_update", spy)

        x, y = random_dataset()
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "FusedAdam", "params": {"lr": 1e-2}}}
        engine, _, loader, _ = deepspeed_tpu.initialize(model=SimpleModel(), config=cfg,
                                                        training_data=(x, y))
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader

        it = iter(RepeatingLoader(loader))
        losses = [float(engine.train_batch(it)) for _ in range(10)]
        assert losses[-1] < losses[0]
        assert isinstance(engine.state.opt_state, FusedAdamState), \
            "FusedAdam config did not build the fused transformation"
        assert calls, "Pallas fused_adam_update kernel was never traced"

    def test_engine_fused_adam_matches_adamw(self):
        """Fused kernel numerics track the plain optax path through the
        engine (same data, same seeds)."""
        import deepspeed_tpu
        from tests.unit.simple_model import SimpleModel, random_dataset
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader

        losses = {}
        for typ in ("FusedAdam", "AdamW"):
            x, y = random_dataset()
            cfg = {"train_micro_batch_size_per_gpu": 1,
                   "optimizer": {"type": typ,
                                 "params": {"lr": 1e-2, "weight_decay": 0.01}}}
            engine, _, loader, _ = deepspeed_tpu.initialize(
                model=SimpleModel(), config=cfg, training_data=(x, y))
            it = iter(RepeatingLoader(loader))
            losses[typ] = [float(engine.train_batch(it)) for _ in range(5)]
        np.testing.assert_allclose(losses["FusedAdam"], losses["AdamW"],
                                   rtol=2e-4, atol=1e-5)

    def test_muon_optimizer_trains(self):
        """"Muon" config type (previously a phantom import) builds and trains."""
        import deepspeed_tpu
        from tests.unit.simple_model import SimpleModel, random_dataset
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader

        x, y = random_dataset()
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "Muon", "params": {"lr": 2e-2}}}
        engine, _, loader, _ = deepspeed_tpu.initialize(model=SimpleModel(), config=cfg,
                                                        training_data=(x, y))
        it = iter(RepeatingLoader(loader))
        losses = [float(engine.train_batch(it)) for _ in range(10)]
        assert losses[-1] < losses[0]


def test_norm_backward_multiblock_grid():
    """rows > 256 exercises the multi-step grid accumulation of dgamma/dbeta
    (zero-on-first-step + VMEM '+=' across sequential grid steps)."""
    import jax, numpy as np
    import jax.numpy as jnp
    from deepspeed_tpu.ops.pallas import layer_norm, rms_norm

    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(rng, (512, 128), jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(8), (128,)) * 0.1 + 1.0
    b = jax.random.normal(jax.random.PRNGKey(9), (128,)) * 0.1

    def loss_pallas(x, g, b):
        return jnp.sum(layer_norm(x, g, b, 1e-5, "interpret") ** 2)

    def loss_xla(x, g, b):
        return jnp.sum(layer_norm(x, g, b, 1e-5, "xla") ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, g, b)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(x, g, b)
    for a, e in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-4)

    def rms_pallas(x, g):
        return jnp.sum(rms_norm(x, g, 1e-6, "interpret") ** 2)

    def rms_xla(x, g):
        return jnp.sum(rms_norm(x, g, 1e-6, "xla") ** 2)

    gp = jax.grad(rms_pallas, argnums=(0, 1))(x, g)
    gx = jax.grad(rms_xla, argnums=(0, 1))(x, g)
    for a, e in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=2e-4, atol=2e-4)


class TestFusedLamb:
    """Fused LAMB kernel parity (reference: csrc/lamb; SURVEY.md §2.2)."""

    def test_kernel_matches_xla_reference(self, rng):
        from deepspeed_tpu.ops.pallas.fused_lamb import fused_lamb_update

        p = jax.random.normal(rng, (300,)) * 0.1
        g = jax.random.normal(jax.random.fold_in(rng, 1), (300,))
        m = jnp.zeros((300,), jnp.float32)
        v = jnp.zeros((300,), jnp.float32)
        step = jnp.asarray(1, jnp.int32)
        for i in range(3):
            step = jnp.asarray(i + 1, jnp.int32)
            ref = fused_lamb_update(p, g, m, v, step, lr=1e-2,
                                    weight_decay=0.01, impl="xla")
            ker = fused_lamb_update(p, g, m, v, step, lr=1e-2,
                                    weight_decay=0.01, impl="interpret")
            for a, b in zip(ref, ker):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)
            p, m, v = ref

    def test_engine_routes_fusedlamb(self):
        from tests.unit.simple_model import SimpleModel, random_dataset
        import deepspeed_tpu

        x, y = random_dataset(n=16)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "FusedLamb", "params": {"lr": 5e-3}}}
        engine, opt, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16), config=cfg,
            rng=jax.random.PRNGKey(0))
        from deepspeed_tpu.ops.pallas.fused_lamb import FusedLambState

        assert isinstance(engine.state and engine.state.opt_state
                          or opt.init({"w": jnp.ones((2,))}), object)
        losses = []
        for _ in range(8):
            loss = engine.forward((x[:8], y[:8]))
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        assert isinstance(engine.state.opt_state, FusedLambState)


class TestDeepSpeedTransformerLayer:
    """Fused encoder layer (reference: ops/transformer; SURVEY.md §2.1)."""

    def test_forward_backward_and_mask(self, rng):
        from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                                   DeepSpeedTransformerLayer)

        cfg = DeepSpeedTransformerConfig(hidden_size=64, intermediate_size=128,
                                         heads=4)
        layer = DeepSpeedTransformerLayer(cfg)
        p = layer.init(rng)
        x = jax.random.normal(jax.random.fold_in(rng, 1), (2, 16, 64))
        y = jax.jit(layer.apply)(p, x)
        assert y.shape == x.shape
        g = jax.grad(lambda p: layer.apply(p, x).astype(jnp.float32).sum())(p)
        assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
        # key padding mask: masked keys must not influence real positions
        mask = np.ones((2, 16), np.int32)
        mask[:, 8:] = 0
        y_mask = layer.apply(p, x, attention_mask=jnp.asarray(mask))
        x2 = x.at[:, 8:].set(0.0)  # change padded content
        y_mask2 = layer.apply(p, x2, attention_mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(y_mask[:, :8]),
                                   np.asarray(y_mask2[:, :8]),
                                   rtol=1e-4, atol=1e-5)


class TestQuantizerKernels:
    """Pallas block quant/dequant (reference: csrc/quantization)."""

    @pytest.mark.parametrize("bits", [8, 4])
    def test_roundtrip_error_bound(self, rng, bits):
        from deepspeed_tpu.ops.pallas.quantizer import dequantize, quantize

        x = jax.random.normal(rng, (5000,)) * 2.0
        q, scale, pad = quantize(x, bits=bits, block=256, impl="interpret")
        out = dequantize(q, scale, pad, x.shape)
        qmax = 127 if bits == 8 else 7
        bound = float(jnp.abs(x).max()) / qmax + 1e-6
        assert np.abs(np.asarray(out - x)).max() <= bound

    def test_kernel_matches_xla(self, rng):
        from deepspeed_tpu.ops.pallas.quantizer import quantize

        x = jax.random.normal(rng, (4096,))
        qk, sk, _ = quantize(x, block=512, impl="interpret")
        qx, sx, _ = quantize(x, block=512, impl="xla")
        np.testing.assert_array_equal(np.asarray(qk), np.asarray(qx))
        np.testing.assert_allclose(np.asarray(sk), np.asarray(sx), rtol=1e-6)

    def test_int4_pack_roundtrip(self, rng):
        from deepspeed_tpu.ops.pallas.quantizer import (pack_int4, quantize,
                                                        unpack_int4)

        x = jax.random.normal(rng, (999,))
        q, scale, pad = quantize(x, bits=4, block=256, impl="xla")
        packed = pack_int4(q)
        restored = unpack_int4(packed, q.size).reshape(q.shape)
        np.testing.assert_array_equal(np.asarray(restored), np.asarray(q))
