"""dslint (tools/dslint.py + deepspeed_tpu/analysis): the whole-repo
zero-violations tier-1 gate, per-rule seeded fixtures, the suppression
reason requirement, the --json schema round-trip, and the DSL003
import-graph check that replaces the per-tool no-jax subprocess asserts
(one subprocess smoke per tool keeps the runtime contract pinned)."""

import json
import os
import subprocess
import sys

import pytest

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")
_REPO = os.path.abspath(os.path.join(_TOOLS, ".."))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "dslint")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _analysis():
    return _tool("dslint")._load_analysis()


def _lint(paths, root, rules=None):
    analysis = _analysis()
    active = None
    if rules is not None:
        active = [r for r in analysis.RULES if r.id in rules]
    findings, project = analysis.run_paths(paths, root=root, rules=active)
    return findings


# ---------------------------------------------------------------------------
# THE tier-1 gate: the whole repo lints clean
# ---------------------------------------------------------------------------


def test_repo_zero_violations(capsys):
    """``python tools/dslint.py deepspeed_tpu tools bench.py`` reports
    ZERO violations — every incident-derived invariant (donation safety,
    sync-free hot paths, jax-free tools, telemetry contracts) holds
    across the package, and every deliberate exception carries a
    reasoned suppression."""
    dslint = _tool("dslint")
    rc = dslint.main(["dslint", os.path.join(_REPO, "deepspeed_tpu"),
                      os.path.join(_REPO, "tools"),
                      os.path.join(_REPO, "bench.py")])
    out = capsys.readouterr().out
    assert rc == 0, f"dslint found violations:\n{out}"
    assert "0 findings" in out


def test_selftest_wired():
    """Every rule fires on its embedded seeded fixture and stays quiet on
    the clean twin (the fleet_dump/ckpt_verify idiom: the offline tool
    cannot silently rot)."""
    dslint = _tool("dslint")
    assert dslint.main(["dslint", "--selftest"]) == 0


# ---------------------------------------------------------------------------
# per-rule seeded fixtures (tests/fixtures/dslint/)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fixture,rule,min_hits", [
    ("dsl001_bad.py", "DSL001", 2),           # donated arg + state sink
    ("dsl002_bad.py", "DSL002", 3),           # disabled branch + 2 syncs
    ("dsl004_bad.py", "DSL004", 1),           # non-ds_ literal
    ("deepspeed_tpu/comm/dsl005_bad.py", "DSL005", 2),  # no scope + cond
    # pipeline boundary form: bare ring hop + scope under a telemetry if
    ("deepspeed_tpu/runtime/pipe/dsl005_pipe_bad.py", "DSL005", 2),
    ("dsl006_bad.py", "DSL006", 3),           # nested / torn / unlocked
])
def test_rule_fires_on_seeded_fixture(fixture, rule, min_hits):
    findings = _lint([os.path.join(_FIXTURES, fixture)], root=_FIXTURES)
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) >= min_hits, \
        f"{rule} expected >= {min_hits} on {fixture}, got " \
        f"{[f.render() for f in findings]}"


def test_dsl003_fires_on_seeded_tree():
    """The DSL003 fixture tree: a 'jax-free' tool reaching jax through a
    helper's normal package import — the finding carries the full chain."""
    root = os.path.join(_FIXTURES, "dsl003_tree")
    findings = _lint(["tools"], root=root)
    hits = [f for f in findings if f.rule == "DSL003"]
    assert hits, [f.render() for f in findings]
    assert "deepspeed_tpu/__init__.py" in hits[0].message
    assert "tools/router.py" in hits[0].message


def test_clean_fixture_zero_findings():
    findings = _lint([os.path.join(_FIXTURES, "clean.py")], root=_FIXTURES)
    assert findings == [], [f.render() for f in findings]


def test_dsl005_pipe_good_twin_clean():
    """The pipeline boundary idiom (conditional RECORD, unconditional
    hop + scope) passes the extended runtime/pipe/ rule scope."""
    findings = _lint([os.path.join(
        _FIXTURES, "deepspeed_tpu/runtime/pipe/dsl005_pipe_good.py")],
        root=_FIXTURES)
    assert findings == [], [f.render() for f in findings]


def test_suppression_without_reason_fails():
    """``# dslint: disable=RULE`` with no ``-- reason``: the original
    finding SURVIVES and the bad directive is its own DSL000 finding."""
    findings = _lint([os.path.join(_FIXTURES, "suppression_no_reason.py")],
                     root=_FIXTURES)
    rules = {f.rule for f in findings}
    assert "DSL002" in rules          # not suppressed
    assert "DSL000" in rules          # the reasonless directive itself
    meta = next(f for f in findings if f.rule == "DSL000")
    assert "justification" in meta.message


def test_suppression_with_reason_suppresses(tmp_path):
    src = (open(os.path.join(_FIXTURES, "suppression_no_reason.py")).read()
           .replace("# dslint: disable=DSL002",
                    "# dslint: disable=DSL002 -- deliberate deferred "
                    "fetch, pinned structurally"))
    p = tmp_path / "case.py"
    p.write_text(src)
    findings = _lint([str(p)], root=str(tmp_path))
    assert findings == [], [f.render() for f in findings]


def test_unknown_rule_in_suppression_is_flagged(tmp_path):
    p = tmp_path / "case.py"
    p.write_text("x = 1  # dslint: disable=DSL999 -- no such rule\n")
    findings = _lint([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["DSL000"]
    assert "unknown rule" in findings[0].message


# ---------------------------------------------------------------------------
# --json schema round-trip
# ---------------------------------------------------------------------------


def test_json_schema_roundtrip(capsys):
    """The --json output is a single JSON object with the pinned schema —
    CI parses it, so the shape is a contract."""
    dslint = _tool("dslint")
    rc = dslint.main(["dslint", "--json",
                      os.path.join(_FIXTURES, "dsl002_bad.py")])
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out)
    assert set(doc) == {"version", "root", "files", "rules", "findings",
                        "counts", "ok"}
    assert doc["version"] == 1 and doc["ok"] is False
    assert doc["files"] == 1 and doc["counts"]["DSL002"] >= 3
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message"}
        assert f["rule"].startswith("DSL")
    # clean run: ok=true, empty findings — same schema
    rc = dslint.main(["dslint", "--json",
                      os.path.join(_FIXTURES, "clean.py")])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["ok"] is True and doc["findings"] == []


# ---------------------------------------------------------------------------
# DSL003 as THE no-jax contract: import-graph wrapper + runtime smokes
# ---------------------------------------------------------------------------


def test_jax_free_tools_import_graph():
    """The whole-graph replacement for the per-tool 'no jax in a fresh
    interpreter' subprocess asserts: every operator tool's static import
    closure (router, fleet_dump, ckpt_verify, train_supervisor,
    trace_report, metrics_dump, dslint itself) stays jax-free."""
    findings = _lint([os.path.join(_REPO, "tools")], root=_REPO,
                     rules={"DSL003"})
    assert findings == [], "\n".join(f.render() for f in findings)


@pytest.mark.parametrize("tool,args,expect", [
    ("dslint.py", ["--selftest"], "dslint selftest: OK"),
    ("fleet_dump.py", ["--selftest"], "fleet_dump selftest: OK"),
    ("ckpt_verify.py", ["--selftest"], "ckpt_verify selftest: OK"),
    ("trace_report.py", ["--selftest"], "trace_report selftest: OK"),
])
def test_tool_subprocess_smoke(tool, args, expect):
    """ONE fresh-interpreter smoke per tool pins the RUNTIME half of the
    no-jax contract (DSL003 pins the static half); tools/router.py's
    smoke lives in test_router.py."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, tool)] + args,
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert expect in proc.stdout


# ---------------------------------------------------------------------------
# regression pins: the rules catch the ORIGINAL incidents re-introduced
# into the real files (mutation tests on copies)
# ---------------------------------------------------------------------------


def _mutate(tmp_path, rel, old, new):
    src = open(os.path.join(_REPO, rel)).read()
    assert old in src, f"mutation anchor drifted in {rel}"
    dst = tmp_path / rel
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(src.replace(old, new))
    return str(dst)


def test_dsl001_catches_reverted_owned_put(tmp_path):
    """Reverting this PR's _step_param_offload fix (raw device_put back
    into the donated state) re-fires DSL001 at the same site."""
    p = _mutate(
        tmp_path, "deepspeed_tpu/runtime/engine.py",
        "new_params = _owned_device_put_tree(compute,\n"
        "                                                self._param_shardings)",
        "new_params = jax.device_put(compute, self._param_shardings)")
    findings = _lint([p], root=str(tmp_path), rules={"DSL001"})
    assert any("_replace(params=" in f.message for f in findings), \
        [f.render() for f in findings]


def test_dsl005_catches_stripped_scope(tmp_path):
    """Deleting a ds_comm_ named_scope from the real comm wrapper file
    re-fires DSL005 (the PR 3 compiled-program-stability contract)."""
    p = _mutate(
        tmp_path, "deepspeed_tpu/comm/comm.py",
        '    with _scope("ds_comm_all_gather"):\n'
        "        return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)",
        "    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)")
    findings = _lint([p], root=str(tmp_path), rules={"DSL005"})
    assert any("all_gather" in f.message for f in findings), \
        [f.render() for f in findings]


def test_dsl004_catches_new_uncapped_bench_block(tmp_path):
    """Adding a dict-valued BENCH_JSON summary block without listing it
    in the final-line cap's victim tuple re-fires the BENCH_r05 guard."""
    p = _mutate(
        tmp_path, "bench.py",
        'summary = {"metric": record["metric"], "value": record["value"],',
        'summary = {"metric": record["metric"], "value": record["value"],')
    # inject an uncapped block right after the core dict is built
    src = open(p).read().replace(
        '    if record["detail"].get("metrics"):',
        '    summary["shiny_new_block"] = {"a": 1}\n'
        '    if record["detail"].get("metrics"):')
    open(p, "w").write(src)
    findings = _lint([p], root=str(tmp_path), rules={"DSL004"})
    assert any("shiny_new_block" in f.message for f in findings), \
        [f.render() for f in findings]
