"""Device-truth layer tests (ISSUE 5 tentpole).

Golden synthetic perfetto traces exercise the post-processor's track
classification (device process vs host threads vs CPU hlo_op proxy rows),
scope matching (event names AND tf_op-style args), interval-union phase
arithmetic (fwd_bwd/optimizer/comm/other/gap partition the window), the
registry backfill (``ds_comm_*_device_seconds`` distinct from the analytic
series), graceful degradation on host-only traces, and the live
``/profilez`` endpoint against a real CPU training engine.
"""

import gzip
import json
import threading
import urllib.error
import urllib.request

import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry
from deepspeed_tpu.profiling import device_trace
from tests.unit.simple_model import SimpleModel, random_dataset

# ---------------------------------------------------------------------------
# synthetic trace builder
# ---------------------------------------------------------------------------

DEV_PID, HOST_PID = 1, 2
OPS_TID, SCOPE_TID, STEPS_TID, PY_TID = 10, 11, 12, 20


def _meta(pid, pname, threads):
    evs = [{"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": pname}}]
    for tid, tname in threads:
        evs.append({"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                    "args": {"name": tname}})
    return evs


def _x(name, pid, tid, ts, dur, args=None):
    e = {"ph": "X", "name": name, "pid": pid, "tid": tid,
         "ts": float(ts), "dur": float(dur)}
    if args:
        e["args"] = args
    return e


def _write(tmp_path, events, name="perfetto_trace.json.gz"):
    p = tmp_path / name
    with gzip.open(p, "wt") as fh:
        json.dump({"displayTimeUnit": "ns", "traceEvents": events}, fh)
    return str(p)


def golden_trace(tmp_path):
    """Two 100us steps on a TPU-style device process, plus host ranges.

    Step layout (us), identical at offsets 0 and 100:
      [0, 60)  fwd/bwd ops (scope via tf_op arg), containing
      [20, 40) an all_gather comm op (nested inside fwd_bwd)
      [60, 80) optimizer-step fusion (scope via the name-scope lane)
      [80, 90) a reduce_scatter comm op (outside fwd_bwd)
      [90,100) device idle (the gap)
    """
    evs = _meta(DEV_PID, "/device:TPU:0", [
        (OPS_TID, "XLA Ops"), (SCOPE_TID, "TensorFlow Name Scope"),
        (STEPS_TID, "Steps")])
    evs += _meta(HOST_PID, "/host:CPU", [(PY_TID, "python")])
    for base in (0, 100):
        evs.append(_x("fusion.1", DEV_PID, OPS_TID, base + 0, 20,
                      {"tf_op": "jit_step/ds_fwd_bwd/fusion.1"}))
        evs.append(_x("all-gather-start.2", DEV_PID, OPS_TID, base + 20, 20,
                      {"tf_op": "jit_step/ds_fwd_bwd/ds_comm_all_gather/"
                                "all-gather.2"}))
        evs.append(_x("fusion.3", DEV_PID, OPS_TID, base + 40, 20,
                      {"tf_op": "jit_step/ds_fwd_bwd/fusion.3"}))
        evs.append(_x("fusion.4", DEV_PID, OPS_TID, base + 60, 20))
        # optimizer scope carried by the dedicated name-scope lane, not args
        evs.append(_x("ds_optimizer_step", DEV_PID, SCOPE_TID, base + 60, 20))
        evs.append(_x("reduce-scatter.5", DEV_PID, OPS_TID, base + 80, 10,
                      {"tf_op": "jit_step/ds_comm_reduce_scatter/rs.5"}))
        # a whole-step summary row that must NOT inflate the busy union
        evs.append(_x("step", DEV_PID, STEPS_TID, base, 100))
        # host-side dispatch range (python thread)
        evs.append(_x("ds_fwd_bwd", HOST_PID, PY_TID, base + 0, 55))
    return _write(tmp_path, evs)


# ---------------------------------------------------------------------------
# parser / summarizer
# ---------------------------------------------------------------------------


def test_golden_phase_breakdown(tmp_path):
    s = device_trace.summarize_trace(golden_trace(tmp_path), steps=2)
    assert not s["degraded"]
    assert s["steps"] == 2
    us = 1e-6
    # window spans first device-op start .. last device-op end = [0, 190]us
    assert s["window_s"] == pytest.approx(190 * us)
    ph = s["phases"]
    # per step: fwd_bwd 60 minus nested 20us comm = 40; optimizer 20;
    # comm 20 (nested all_gather) + 10 (reduce_scatter) = 30; gap 10us
    # between steps (90..100); nothing unclaimed
    assert ph["fwd_bwd_s"] == pytest.approx(2 * 40 * us)
    assert ph["optimizer_s"] == pytest.approx(2 * 20 * us)
    assert ph["comm_s"] == pytest.approx(2 * 30 * us)
    assert ph["other_s"] == pytest.approx(0.0, abs=1e-12)
    assert ph["gap_s"] == pytest.approx(10 * us)  # one inter-step idle
    # the five phases partition the window exactly
    assert sum(ph.values()) == pytest.approx(s["window_s"])
    assert s["per_step"]["fwd_bwd_s"] == pytest.approx(40 * us)


def test_lane_rows_padding_past_ops_keep_partition_exact(tmp_path):
    """Name-scope lane spans can pad past the op rows and bridge the idle
    between them (real xplane exports merge adjacent same-scope ops into
    one lane span); scopes must clamp to the busy union so phases + gap
    still partition the window exactly."""
    evs = _meta(DEV_PID, "/device:TPU:0", [
        (OPS_TID, "XLA Ops"), (SCOPE_TID, "TensorFlow Name Scope")])
    evs.append(_x("fusion.1", DEV_PID, OPS_TID, 100, 300))
    evs.append(_x("all-gather.2", DEV_PID, OPS_TID, 500, 100))
    # lane spans 0..800: pads before/after the ops AND bridges 400..500 idle
    evs.append(_x("ds_fwd_bwd", DEV_PID, SCOPE_TID, 0, 800))
    evs.append(_x("ds_comm_all_gather", DEV_PID, SCOPE_TID, 450, 200))
    s = device_trace.summarize_trace(_write(tmp_path, evs), steps=1)
    us = 1e-6
    ph = s["phases"]
    assert s["window_s"] == pytest.approx(500 * us)    # ops span 100..600
    assert ph["comm_s"] == pytest.approx(100 * us)     # busy inside the lane
    assert ph["fwd_bwd_s"] == pytest.approx(300 * us)  # busy - comm
    assert ph["gap_s"] == pytest.approx(100 * us)      # the 400..500 idle
    assert sum(ph.values()) == pytest.approx(s["window_s"])


def test_comm_scope_entirely_over_idle_is_dropped(tmp_path):
    """A comm name-scope lane span lying wholly over device-idle time
    clips to nothing against the busy union — it must vanish from
    comm_device, not crash the summarizer (max() over an empty union)."""
    evs = _meta(DEV_PID, "/device:TPU:0", [
        (OPS_TID, "XLA Ops"), (SCOPE_TID, "TensorFlow Name Scope")])
    evs.append(_x("fusion.1", DEV_PID, OPS_TID, 0, 100))
    # comm lane over 200..300: no op row anywhere under it
    evs.append(_x("ds_comm_all_reduce", DEV_PID, SCOPE_TID, 200, 100))
    s = device_trace.summarize_trace(_write(tmp_path, evs), steps=1)
    assert "all_reduce" not in s["comm_device"]
    assert s["phases"]["comm_s"] == pytest.approx(0.0, abs=1e-12)


def test_golden_comm_device_series_and_backfill(tmp_path):
    s = device_trace.summarize_trace(golden_trace(tmp_path), steps=2)
    cd = s["comm_device"]
    assert cd["all_gather"]["seconds"] == pytest.approx(40e-6)
    assert cd["all_gather"]["count"] == 2
    assert cd["reduce_scatter"]["seconds"] == pytest.approx(20e-6)

    reg = MetricsRegistry().enable()
    # analytic series pre-exists and must be untouched by the backfill
    analytic = reg.histogram("ds_comm_all_gather_seconds")
    analytic.record(0.123)
    device_trace.publish_summary(
        s, reg, bytes_per_op={"all_gather": (4_000_000, 8)})
    h = reg.get("ds_comm_all_gather_device_seconds")
    assert h is not None and h.count == 1
    assert h.sum == pytest.approx(40e-6)
    assert analytic.count == 1 and analytic.sum == pytest.approx(0.123)
    # busbw recomputed from device time: 4MB / 40us = 100 GB/s alg,
    # x (8-1)/8 ring factor
    bw = reg.get("ds_comm_all_gather_device_busbw_gbps").value
    assert bw == pytest.approx(100.0 * 7 / 8, rel=1e-6)
    assert reg.get("ds_profile_gap_seconds").value == pytest.approx(
        s["per_step"]["gap_s"])


def test_golden_overlapped_comm_not_double_subtracted(tmp_path):
    """Comm rows CONCURRENT with compute rows (a second device op lane —
    what the layer-chunked overlap schedule produces): the exclusive
    partition must claim the overlapped time for ``comm`` exactly once
    (never subtract it from gap, which is computed against the busy
    union), phases + gap must still sum to the window, and the
    comm∩compute time must surface as ``overlapped_comm_s`` feeding the
    ``ds_overlap_hidden_comm_seconds_est`` gauge.

    Layout (us), one step, two op lanes:
      lane A [0,100)   fwd/bwd fusion
      lane B [40,80)   all_gather CONCURRENT with fwd/bwd   (hidden, 40)
      lane B [100,120) all_gather after compute             (exposed, 20)
      idle   [120,130)                                      (gap, 10)
      lane A [130,150) optimizer fusion
      lane B [140,150) reduce_scatter CONCURRENT with optimizer (hidden, 10)
    """
    LANE_B = 13
    evs = _meta(DEV_PID, "/device:TPU:0", [
        (OPS_TID, "XLA Ops"), (LANE_B, "XLA Ops c1")])
    evs.append(_x("fusion.1", DEV_PID, OPS_TID, 0, 100,
                  {"tf_op": "jit_step/ds_fwd_bwd/fusion.1"}))
    evs.append(_x("all-gather.2", DEV_PID, LANE_B, 40, 40,
                  {"tf_op": "jit_step/ds_fwd_bwd/ds_comm_all_gather/ag.2"}))
    evs.append(_x("all-gather.3", DEV_PID, LANE_B, 100, 20,
                  {"tf_op": "jit_step/ds_comm_all_gather/ag.3"}))
    evs.append(_x("fusion.4", DEV_PID, OPS_TID, 130, 20,
                  {"tf_op": "jit_step/ds_optimizer_step/fusion.4"}))
    evs.append(_x("reduce-scatter.5", DEV_PID, LANE_B, 140, 10,
                  {"tf_op": "jit_step/ds_optimizer_step/"
                            "ds_comm_reduce_scatter/rs.5"}))
    s = device_trace.summarize_trace(_write(tmp_path, evs), steps=1)
    us = 1e-6
    ph = s["phases"]
    assert s["window_s"] == pytest.approx(150 * us)
    # comm union claims hidden + exposed once: 40 + 20 + 10
    assert ph["comm_s"] == pytest.approx(70 * us)
    # fwd_bwd = its 100us minus the 40us concurrent comm — subtracted ONCE
    assert ph["fwd_bwd_s"] == pytest.approx(60 * us)
    assert ph["optimizer_s"] == pytest.approx(10 * us)
    assert ph["other_s"] == pytest.approx(0.0, abs=1e-12)
    # gap is true idle only — overlapped comm must NOT eat into it
    assert ph["gap_s"] == pytest.approx(10 * us)
    assert sum(ph.values()) == pytest.approx(s["window_s"])
    # the hidden-comm measurement: comm ∩ (fwd_bwd ∪ optimizer)
    assert s["overlapped_comm_s"] == pytest.approx(50 * us)

    reg = MetricsRegistry().enable()
    device_trace.publish_summary(s, reg)
    assert reg.get("ds_overlap_hidden_comm_seconds_est").value == \
        pytest.approx(50 * us)


def test_cpu_proxy_rows_classify_as_device(tmp_path):
    """CPU traces have no /device process; XLA-runtime rows tagged with
    args.hlo_op count as device-proxy op rows, and a scope with host
    ranges but no device matches (the CPU export drops scope paths) gets
    the device-busy time INSIDE its host ranges, flagged host_scoped."""
    evs = _meta(HOST_PID, "/host:CPU", [
        (PY_TID, "python"), (30, "tf_XLATfrtCpuClient/1")])
    evs.append(_x("dot.3", HOST_PID, 30, 0, 50,
                  {"hlo_module": "jit_step", "hlo_op": "dot.3"}))
    evs.append(_x("dot.9", HOST_PID, 30, 70, 20,
                  {"hlo_module": "jit_step", "hlo_op": "dot.9"}))
    evs.append(_x("ds_fwd_bwd", HOST_PID, PY_TID, 0, 60))
    s = device_trace.summarize_trace(_write(tmp_path, evs))
    assert not s["degraded"]
    assert s["device_rows"] == 2
    assert s["device_busy_s"] == pytest.approx(70e-6)
    assert s["host_scoped"] == ["ds_fwd_bwd"]
    # device rows inside the host fwd_bwd range -> fwd_bwd; the row
    # outside any scope stays "other"; gap = [50,70) idle
    assert s["phases"]["fwd_bwd_s"] == pytest.approx(50e-6)
    assert s["phases"]["other_s"] == pytest.approx(20e-6)
    assert s["phases"]["gap_s"] == pytest.approx(20e-6)


def test_degrades_to_host_ranges_without_device_rows(tmp_path):
    """A trace with only host annotation ranges still yields a labeled
    (degraded) phase breakdown instead of crashing or reporting zeros."""
    evs = _meta(HOST_PID, "/host:CPU", [(PY_TID, "python")])
    evs.append(_x("ds_fwd_bwd", HOST_PID, PY_TID, 0, 70))
    evs.append(_x("ds_optimizer_step", HOST_PID, PY_TID, 70, 20))
    s = device_trace.summarize_trace(_write(tmp_path, evs), steps=1)
    assert s["degraded"]
    assert s["phases"]["fwd_bwd_s"] == pytest.approx(70e-6)
    assert s["phases"]["optimizer_s"] == pytest.approx(20e-6)
    assert s["phases"]["gap_s"] == pytest.approx(0.0, abs=1e-12)
    assert sum(s["phases"].values()) == pytest.approx(s["window_s"])


def test_serving_dispatch_slack(tmp_path):
    """Host ds_serve_decode ranges vs device rows inside them: the slack
    (host dispatch window minus device busy) is the sync-free headroom."""
    evs = _meta(DEV_PID, "/device:TPU:0", [(OPS_TID, "XLA Ops")])
    evs += _meta(HOST_PID, "/host:CPU", [(PY_TID, "python")])
    evs.append(_x("ds_serve_decode", HOST_PID, PY_TID, 0, 100))
    evs.append(_x("fusion.9", DEV_PID, OPS_TID, 10, 60))
    s = device_trace.summarize_trace(_write(tmp_path, evs))
    assert s["serve"]["decode_blocks"] == 1
    assert s["serve"]["decode_host_s"] == pytest.approx(100e-6)
    assert s["serve"]["decode_device_s"] == pytest.approx(60e-6)
    assert s["serve"]["dispatch_slack_s"] == pytest.approx(40e-6)
    reg = MetricsRegistry().enable()
    device_trace.publish_summary(s, reg)
    assert reg.get("ds_profile_serve_dispatch_slack_seconds").value == \
        pytest.approx(40e-6)


def test_metrics_dump_device_columns(tmp_path):
    """tools/metrics_dump.py --comms renders the device-truth series next
    to the analytic attribution (side-by-side error reading)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    reg = MetricsRegistry().enable()
    reg.counter("ds_comm_all_gather_calls_total").inc(4)
    reg.counter("ds_comm_all_gather_bytes_total",
                labels={"dtype": "float32"}).inc(1 << 20)
    reg.histogram("ds_comm_all_gather_seconds").record(0.004)
    reg.histogram("ds_comm_all_gather_device_seconds").record(0.001)
    reg.gauge("ds_comm_all_gather_device_busbw_gbps").set(123.0)
    snap = json.loads(reg.statz_json())["metrics"]
    rows = metrics_dump.comms_rows(snap)
    table = metrics_dump.render_comms(rows)
    assert "dev_p50_s" in table and "dev_busbw" in table
    row = rows[0]
    assert row[0] == "all_gather"
    assert row[3] == ""   # dense op: no compression column
    assert row[7] != "" and float(row[7]) == pytest.approx(0.001, rel=0.5)
    assert "123" in row[8]


def test_interval_helpers():
    m = device_trace._merge([(5, 7), (0, 3), (2, 4)])
    assert m == [(0, 4), (5, 7)]
    assert device_trace._union_len([(0, 3), (2, 4), (5, 7)]) == 6
    assert device_trace._subtract([(0, 10)], [(2, 4), (6, 8)]) == \
        [(0, 2), (4, 6), (8, 10)]
    assert device_trace._subtract([(0, 4)], [(0, 10)]) == []


def test_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        device_trace.summarize_trace(str(tmp_path))


# ---------------------------------------------------------------------------
# CPU e2e: live /profilez against a real training engine
# ---------------------------------------------------------------------------


needs_perfetto = pytest.mark.skipif(
    not device_trace.perfetto_supported(),
    reason="this jax's start_trace has no create_perfetto_trace")


@needs_perfetto
def test_profilez_live_training_engine(tmp_path):
    """`/profilez?steps=2` against a stepping engine returns a JSON phase
    summary; ds_fwd_bwd appears (host annotation ranges on CPU); the
    analytic ds_comm series is not touched by the device-truth layer."""
    from deepspeed_tpu.monitor.server import MetricsServer

    x, y = random_dataset(n=16)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "comms_logger": {"enabled": True},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8), config=cfg,
        rng=jax.random.PRNGKey(0))
    server = MetricsServer(get_registry(), port=0).start()
    analytic_before = get_registry().get("ds_comm_all_gather_seconds")
    analytic_count = analytic_before.count if analytic_before else 0

    stop = threading.Event()

    def train():
        while not stop.is_set():
            loss = engine.forward((x[:8], y[:8]))
            engine.backward(loss)
            engine.step()

    t = threading.Thread(target=train, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                f"{server.url}/profilez?steps=2&timeout=120",
                timeout=150) as resp:
            summary = json.load(resp)
    finally:
        stop.set()
        t.join(timeout=30)
        server.stop()
    assert summary["steps"] == 2
    assert summary["window_s"] > 0
    ph = summary["phases"]
    # the breakdown partitions the captured window (within float noise)
    assert sum(ph.values()) == pytest.approx(summary["window_s"], rel=1e-6)
    # ds_fwd_bwd is visible: the engine emits host annotation ranges around
    # the accum dispatch (device named scopes don't survive the CPU export)
    assert ph["fwd_bwd_s"] > 0, summary
    # the device-truth layer never writes the analytic host-window series
    analytic_after = get_registry().get("ds_comm_all_gather_seconds")
    if analytic_after is not None:
        got = analytic_after.count
        # the training thread keeps committing analytic entries; the check
        # is that publish_summary added nothing beyond those commits —
        # device time landed ONLY in the _device_ series
        assert got >= analytic_count
    dev = get_registry().get("ds_profile_window_seconds")
    assert dev is not None and dev.value > 0


@needs_perfetto
def test_profilez_no_engine_times_out():
    """Without a stepping engine the request must clear cleanly (504) and
    leave the broker reusable."""
    from deepspeed_tpu.monitor.server import MetricsServer

    server = MetricsServer(MetricsRegistry().enable(), port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{server.url}/profilez?steps=1&timeout=0.2", timeout=10)
        assert ei.value.code == 504
        broker = device_trace.get_profile_broker()
        assert broker.pending is None
    finally:
        server.stop()
