"""Inert-config auditing (VERDICT r3 item 6): every parsed-but-unread
behavior knob must warn once at engine init — a capability gap must never
hide behind a successfully-parsed config section."""

import logging

import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh
from deepspeed_tpu.utils.logging import logger as ds_logger
from tests.unit.simple_model import SimpleModel


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


@pytest.fixture()
def warnings_log():
    h = _Capture()
    ds_logger.addHandler(h)
    yield h.messages
    ds_logger.removeHandler(h)


def _engine(extra):
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    cfg.update(extra)
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg, mesh=mesh)
    return engine


@pytest.mark.parametrize("section,key", [
    ({"amp": {"enabled": True}}, "amp"),
    ({"sparse_gradients": True}, "sparse_gradients"),
    ({"communication_data_type": "fp32"}, "communication_data_type"),
])
def test_inert_key_warns(section, key, warnings_log):
    engine = _engine(section)
    assert key in engine._inert_config_keys
    assert any("INERT" in m and key in m for m in warnings_log), warnings_log


def test_cpu_checkpointing_offloads_residuals(warnings_log, rng):
    # cpu_checkpointing is now implemented (saved residuals page to pinned
    # host via the offloaded-dots remat policy): no DEGRADED warning, the
    # policy lands on the model, and training still converges.
    import numpy as np

    from deepspeed_tpu.comm.mesh import set_global_mesh
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, max_seq_len=64)
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "activation_checkpointing": {"cpu_checkpointing": True},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               mesh=mesh, rng=rng)
    assert model.config.remat and model.config.remat_policy == "offload_dots"
    toks = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, 256)
    losses = []
    for _ in range(4):
        loss = engine.forward((toks, toks))
        engine.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    assert not any("DEGRADED" in m and "cpu_checkpointing" in m
                   for m in warnings_log), warnings_log


def test_clean_config_has_no_inert_warnings(warnings_log):
    engine = _engine({})
    assert engine._inert_config_keys == []
    assert not any("INERT" in m for m in warnings_log)


def test_zeropp_knobs_warn_when_path_inactive(warnings_log):
    # ZeRO++ knobs on a config the quantized-collective path does not cover
    # must warn rather than silently train dense.
    engine = _engine({"zero_optimization": {
        "stage": 1, "zero_quantized_gradients": True,
        "zero_quantized_weights": True, "zero_hpz_partition_size": 2}})
    if engine._zeropp_active():
        pytest.skip("ZeRO++ active for this config; nothing inert")
    joined = " ".join(engine._inert_config_keys)
    assert "zero_quantized_gradients" in joined
    assert "zero_quantized_weights" in joined
    assert "zero_hpz_partition_size" in joined
