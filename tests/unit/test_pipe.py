"""Pipeline-parallel tests (reference analog: tests/unit/runtime/pipe/,
SURVEY.md §4): parity of the SPMD pipeline against sequential execution,
and end-to-end training of the built-in model over a pp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.runtime.pipe import (LayerSpec, PipelineModule, spmd_pipeline)


class TanhLayer:
    def __init__(self, dim):
        self.dim = dim

    def init(self, rng, x):
        return {"w": jax.random.normal(rng, (self.dim, self.dim)) * 0.3}

    def apply(self, params, x):
        return jnp.tanh(x @ params["w"])


def test_spmd_pipeline_matches_sequential(devices, rng):
    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    L, D, B, M = 8, 16, 8, 4
    w = jax.random.normal(rng, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    def stage_fn(wl, xmb, _scan, *bcast):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        y, _ = jax.lax.scan(body, xmb, wl)
        return y, jnp.zeros((), jnp.float32)

    def sequential(w, x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    y, aux = jax.jit(lambda w, x: spmd_pipeline(stage_fn, w, x, mesh,
                                                num_microbatches=M))(w, x)
    ref = sequential(w, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # gradients through the pipeline == sequential gradients
    gp = jax.jit(jax.grad(lambda w: jnp.sum(
        spmd_pipeline(stage_fn, w, x, mesh, num_microbatches=M)[0] ** 2)))(w)
    gs = jax.jit(jax.grad(lambda w: jnp.sum(sequential(w, x) ** 2)))(w)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), rtol=1e-4, atol=1e-5)


def test_pipeline_module_api(devices, rng):
    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    D = 16
    module = PipelineModule([LayerSpec(TanhLayer, D) for _ in range(8)], mesh=mesh)
    x = jax.random.normal(rng, (8, D))
    params = module.init(rng, x)
    assert jax.tree.leaves(params)[0].shape[0] == 8  # stacked layer dim
    y = jax.jit(module.apply)(params, x)
    xs = x
    for i in range(8):
        xs = jnp.tanh(xs @ jax.tree.map(lambda a: a[i], params)["w"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(xs), rtol=1e-5, atol=1e-5)


def test_model_trains_on_pp_mesh(devices, rng):
    """Llama-family model end-to-end on pp=2 × fsdp=2 × tp=2."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(pp=2, fsdp=2, tp=2, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=4, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256)
    ds_config = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
                 "zero_optimization": {"stage": 1},
                 "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                 "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config, mesh=mesh)
    toks = jax.random.randint(rng, (8, 64), 0, 256)
    losses = []
    for _ in range(4):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pp_forward_matches_no_pp(devices, rng):
    """Same params, same tokens: pipelined forward == unpipelined forward."""
    from deepspeed_tpu.models import causal_lm

    toks = jax.random.randint(rng, (4, 32), 0, 128)
    kw = dict(num_layers=4, hidden_size=32, intermediate_size=64, num_heads=2,
              num_kv_heads=2, vocab_size=128, remat=False)

    mesh1 = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh1)
    m1 = causal_lm("llama-tiny", mesh=mesh1, **kw)
    params = m1.init(rng, toks)
    ref = jax.jit(m1.apply)(params, toks)

    mesh2 = build_mesh(pp=4, fsdp=2, devices=devices)
    set_global_mesh(mesh2)
    m2 = causal_lm("llama-tiny", mesh=mesh2, **kw)
    out = jax.jit(m2.apply)(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_1f1b_matches_gpipe(devices, rng):
    """VERDICT r4 item 2 done-criterion (parity): the fused 1F1B schedule
    produces the same loss and parameter gradients as the autodiff GPipe
    path on a real model at pp=4, M=16."""
    from deepspeed_tpu.models import causal_lm

    toks = jax.random.randint(rng, (16, 32), 0, 128)
    kw = dict(num_layers=8, hidden_size=32, intermediate_size=64, num_heads=2,
              num_kv_heads=2, vocab_size=128, remat=False, pp_microbatches=16)
    mesh = build_mesh(pp=4, fsdp=2, devices=devices)
    set_global_mesh(mesh)

    m_g = causal_lm("llama-tiny", mesh=mesh, pp_schedule="gpipe", **kw)
    params = m_g.init(rng, toks)
    loss_g, grads_g = jax.jit(jax.value_and_grad(
        lambda p: m_g.apply(p, toks, labels=toks)))(params)

    m_f = causal_lm("llama-tiny", mesh=mesh, pp_schedule="1f1b", **kw)
    loss_f, grads_f = jax.jit(jax.value_and_grad(
        lambda p: m_f.apply(p, toks, labels=toks)))(params)

    np.testing.assert_allclose(float(loss_f), float(loss_g),
                               rtol=1e-5, atol=1e-6)
    for (kg, gg), (_, gf) in zip(
            jax.tree_util.tree_leaves_with_path(grads_g),
            jax.tree_util.tree_leaves_with_path(grads_f)):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gg),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=jax.tree_util.keystr(kg))


def test_engine_trains_with_1f1b_schedule(devices, rng):
    """ds_config pipeline.schedule="1f1b" reaches the model and the engine
    trains through the fused schedule (reference PipelineEngine +
    TrainSchedule wiring)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(pp=2, fsdp=2, tp=2, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=4, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256)
    ds_config = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
                 "zero_optimization": {"stage": 1},
                 "pipeline": {"schedule": "1f1b", "micro_batches": 4},
                 "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                 "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config,
                                               mesh=mesh)
    assert model.config.pp_schedule == "1f1b"
    assert model.config.pp_microbatches == 4
    toks = jax.random.randint(rng, (8, 64), 0, 256)
    losses = []
    for _ in range(4):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    with pytest.raises(ValueError, match="schedule"):
        deepspeed_tpu.initialize(
            model=causal_lm("llama-tiny", mesh=mesh, num_layers=4,
                            hidden_size=64, intermediate_size=128,
                            num_heads=4, num_kv_heads=2, vocab_size=256),
            config={**ds_config, "pipeline": {"schedule": "interleaved"}},
            mesh=mesh)


def test_1f1b_bounds_inflight_boundaries(devices, rng):
    """VERDICT r4 item 2 done-criterion (memory): at pp=4, M=16 the fused
    1F1B program's live boundary stash is the 2pp-1 circular buffer, not
    the GPipe scan's M+pp-1 saved steps — measured with the compiled
    memory_analysis (the technique from test_param_offload.py)."""
    from deepspeed_tpu.models import causal_lm

    B, S, M = 32, 512, 32
    toks = jax.random.randint(rng, (B, S), 0, 256)
    # boundary-dominant shapes: each stashed boundary is 1x512x512 fp32
    # (1MB), so the GPipe scan's 35 saved steps vs 1F1B's 7 circular slots
    # is the dominant temp-pool difference
    kw = dict(num_layers=4, hidden_size=512, intermediate_size=512,
              num_heads=4, num_kv_heads=4, vocab_size=256, remat=False,
              pp_microbatches=M)
    mesh = build_mesh(pp=4, fsdp=2, devices=devices)
    set_global_mesh(mesh)

    def temp_bytes(schedule):
        m = causal_lm("llama-tiny", mesh=mesh, pp_schedule=schedule, **kw)
        params = jax.eval_shape(m.init, rng, toks)
        params = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), params)
        fn = jax.jit(jax.value_and_grad(lambda p: m.apply(p, toks,
                                                          labels=toks)))
        ma = fn.lower(params).compile().memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend exposes no memory analysis")
        return ma.temp_size_in_bytes

    gpipe, f1b = temp_bytes("gpipe"), temp_bytes("1f1b")
    # the live boundary stash shrinks from the GPipe scan's (M+pp-1)=35
    # saved steps to the 1F1B circular buffer's (2pp-1)=7 slots.  Assert
    # the temp-pool DELTA accounts for most of that slot-count shrink (the
    # x/gx/grad pools are shared between the two programs and dominate the
    # absolute numbers, so a ratio would mostly measure the model, not the
    # schedule).
    slot = 1 * S * 512 * 4  # one boundary microbatch [mb=1, S, D] fp32
    shrink = ((M + 4 - 1) - (2 * 4 - 1)) * slot
    assert f1b < gpipe, (f1b, gpipe)
    assert gpipe - f1b > 0.7 * shrink, (f1b, gpipe, shrink)


def _walk_eqns(jaxpr, acc):
    for eqn in jaxpr.eqns:
        acc.append(eqn)
        for v in eqn.params.values():
            for u in (v if isinstance(v, (tuple, list)) else [v]):
                inner = getattr(u, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _walk_eqns(inner, acc)
                elif hasattr(u, "eqns"):
                    _walk_eqns(u, acc)
    return acc


def test_pp_boundary_crosses_in_bf16(devices, rng):
    """VERDICT r3 weak #2 done-criterion: with the TPU boundary mode
    (boundary_fp32=False) no non-scalar fp32 tensor crosses the pp axis —
    ppermute and psum payloads stay bf16, halving stage-to-stage ICI bytes.
    Trace-only: executing bf16 boundary psum CHECK-crashes the XLA *CPU*
    backend (the reason the gate exists), so this asserts on the jaxpr."""
    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    L, D, B, M = 8, 16, 32, 16
    w = jax.random.normal(rng, (L, D, D)).astype(jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D)).astype(jnp.bfloat16)

    def stage_fn(wl, xmb, _scan, *bcast):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        y, _ = jax.lax.scan(body, xmb, wl)
        return y, jnp.zeros((), jnp.float32)

    def loss(w, x):
        y, _ = spmd_pipeline(stage_fn, w, x, mesh, num_microbatches=M,
                             boundary_fp32=False)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    eqns = _walk_eqns(jax.make_jaxpr(jax.grad(loss))(w, x).jaxpr, [])
    comm = [e for e in eqns if e.primitive.name in ("ppermute", "psum",
                                                    "psum_invariant")]
    assert comm, "no collectives found in pipelined jaxpr"
    for e in comm:
        for v in e.invars:
            aval = v.aval
            # scalar carries (aux/loss accumulators, promoted to (1,) to
            # keep scan residuals rank>=1) may be fp32
            if getattr(aval, "size", 1) > 1:
                assert aval.dtype == jnp.bfloat16, (
                    f"{e.primitive.name} carries {aval.dtype}{aval.shape}")


def test_pipeline_remat_bounds_residuals(devices, rng):
    """VERDICT r3 weak #3 done-criterion: pp=4, M=16 — with remat_stage the
    scan's backward residuals are bounded by the boundary tensors, not the
    stage-body internals."""
    from jax._src.ad_checkpoint import saved_residuals

    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    L, D, B, M = 8, 16, 64, 16
    w = jax.random.normal(rng, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    def stage_fn(wl, xmb, _scan, *bcast):
        def body(c, wi):
            h = jnp.tanh(c @ wi)
            return jnp.tanh(h @ wi.T) + c, None
        y, _ = jax.lax.scan(body, xmb, wl)
        return y, jnp.zeros((), jnp.float32)

    def loss(w, remat):
        y, _ = spmd_pipeline(stage_fn, w, x, mesh, num_microbatches=M,
                             remat_stage=remat)
        return jnp.sum(y ** 2)

    def res_bytes(remat):
        res = saved_residuals(lambda w: loss(w, remat), w)
        return sum(int(np.prod(r[0].shape)) * r[0].dtype.itemsize for r in res)

    full, bounded = res_bytes(False), res_bytes(True)
    # full saves the two tanh internals per layer per step; bounded saves the
    # per-step boundary input (plus loop constants).  Empirically ~4x here;
    # assert a conservative 2.5x so dtype/layout drift doesn't flake.
    assert bounded * 2.5 < full, (full, bounded)

    # remat changes memory, never math
    gp = jax.jit(jax.grad(lambda w: loss(w, True)))(w)
    gs = jax.jit(jax.grad(lambda w: loss(w, False)))(w)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), rtol=1e-4,
                               atol=1e-5)


def test_pp_loss_matches_no_pp(devices, rng):
    """Loss-in-pipeline (scalar reduction on the last stage) must equal the
    unpipelined loss — and the pipelined program must NOT materialize the
    replicated [B, S, D] hidden buffer (VERDICT r2 weak #5)."""
    from deepspeed_tpu.models import causal_lm

    toks = jax.random.randint(rng, (8, 32), 0, 256)
    kw = dict(num_layers=4, hidden_size=64, intermediate_size=128,
              num_heads=4, num_kv_heads=2, vocab_size=256, remat=False,
              ce_chunk=0)
    mesh_pp = build_mesh(pp=2, fsdp=2, tp=2, devices=devices)
    set_global_mesh(mesh_pp)
    model_pp = causal_lm("llama-tiny", mesh=mesh_pp, **kw)
    params = model_pp.init(jax.random.PRNGKey(3), toks)
    loss_pp = jax.jit(lambda p: model_pp.apply(p, toks, labels=toks))(params)

    mesh1 = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh1)
    model1 = causal_lm("llama-tiny", mesh=mesh1, **kw)
    loss1 = jax.jit(lambda p: model1.apply(p, toks, labels=toks))(params)
    np.testing.assert_allclose(float(loss_pp), float(loss1), rtol=2e-5)


# ---------------------------------------------------------------------------
# PartitionId-class retirement (ISSUE 16): loss/grad parity matrix across
# pp degrees x microbatch counts, INCLUDING an uneven last microbatch (the
# transformer pads the batch to a multiple of M with label=-1 / mask=0 rows)
# ---------------------------------------------------------------------------

def _tiny_lm_kw():
    return dict(num_layers=4, hidden_size=64, intermediate_size=128,
                num_heads=4, num_kv_heads=2, vocab_size=256, remat=False,
                ce_chunk=0)


@pytest.mark.parametrize("pp,fsdp,M,B,schedule", [
    (2, 4, 2, 8, "gpipe"),    # even split
    (2, 4, 3, 8, "gpipe"),    # uneven: 8 % 3 -> pad to 9, mb=3
    (4, 2, 4, 8, "gpipe"),    # even, deeper pipeline
    (4, 2, 5, 8, "gpipe"),    # uneven: 8 % 5 -> pad to 10, mb=2
    (2, 4, 3, 8, "1f1b"),     # uneven through the fused fwd+bwd scan
    (4, 2, 4, 8, "1f1b"),     # even through the fused scan, pp=4
])
def test_pp_loss_grad_parity_matrix(devices, pp, fsdp, M, B, schedule):
    """Full-manual pipelined loss AND parameter grads match the
    unpipelined fsdp=8 reference on the same params — across pipeline
    depths, microbatch counts (uneven last microbatch included) and both
    schedules.  This is the real retirement of the 9 PartitionId tier-1
    failures: the programs now compile AND are numerically right."""
    from deepspeed_tpu.models import causal_lm

    toks = jax.random.randint(jax.random.PRNGKey(11), (B, 32), 0, 256)
    kw = _tiny_lm_kw()
    mesh_pp = build_mesh(pp=pp, fsdp=fsdp, devices=devices)
    set_global_mesh(mesh_pp)
    model_pp = causal_lm("llama-tiny", mesh=mesh_pp, pp_microbatches=M,
                         pp_schedule=schedule, **kw)
    params = model_pp.init(jax.random.PRNGKey(3), toks)

    def loss_pp(p):
        return model_pp.apply(p, toks, labels=toks)

    lp, gp = jax.jit(jax.value_and_grad(loss_pp))(params)

    mesh1 = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh1)
    model1 = causal_lm("llama-tiny", mesh=mesh1, **kw)

    def loss1(p):
        return model1.apply(p, toks, labels=toks)

    l1, g1 = jax.jit(jax.value_and_grad(loss1))(params)
    np.testing.assert_allclose(float(lp), float(l1), rtol=3e-5)
    flat_p, _ = jax.tree.flatten(gp)
    flat_1, _ = jax.tree.flatten(g1)
    for a, b in zip(flat_p, flat_1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-6)


# ---------------------------------------------------------------------------
# quantized stage boundary: parity + the double byte ledger on one trace
# ---------------------------------------------------------------------------

def test_pp_quantized_boundary_parity_and_ledger(devices, rng):
    """int8 boundary rings track the dense pipeline closely (one blockwise
    quantization error per hop) and the trace-time double ledger pins the
    wire reduction: q_ppermute moves >=2x fewer bytes than its dense twin
    (int8 codes + fp32 block scales vs the fp32 activation)."""
    from deepspeed_tpu.monitor.comms import CommMetrics
    from deepspeed_tpu.monitor.metrics import MetricsRegistry
    import deepspeed_tpu.comm.collectives_q as cq_mod

    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    L, D, B, M = 8, 256, 8, 4
    # 0.15 keeps the tanh stack roughly norm-preserving; at 0.3 each
    # matmul amplifies the per-hop quantization error ~0.3*sqrt(D) ~ 4.8x
    # and the test would measure the toy network's conditioning, not the
    # boundary codec
    w = jax.random.normal(rng, (L, D, D)) * 0.15
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    def stage_fn(wl, xmb, _scan, *bcast):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        y, _ = jax.lax.scan(body, xmb, wl)
        return y, jnp.zeros((), jnp.float32)

    def run(w, x, quant):
        return spmd_pipeline(stage_fn, w, x, mesh, num_microbatches=M,
                             quantize_boundary=quant)[0]

    # per-element error is amplified by the downstream tanh(c @ w) layers
    # (~0.3*sqrt(D) per matmul), so the parity contract is LOSS parity —
    # what the bench rung pins — not elementwise activation identity
    y_d = jax.jit(lambda w, x: run(w, x, False))(x=x, w=w)
    y_q = jax.jit(lambda w, x: run(w, x, True))(x=x, w=w)
    diff = np.asarray(y_q) - np.asarray(y_d)
    assert 0 < float(np.abs(diff).max()) < 0.5   # perturbed, not broken
    ld = float(np.mean(np.asarray(y_d) ** 2))
    lq = float(np.mean(np.asarray(y_q) ** 2))
    assert abs(lq - ld) < 0.02 * abs(ld), (lq, ld)

    # grads flow through the quantized reverse ring and stay close in L2
    gd = np.asarray(jax.jit(jax.grad(
        lambda w: jnp.mean(run(w, x, False) ** 2)))(w))
    gq = np.asarray(jax.jit(jax.grad(
        lambda w: jnp.mean(run(w, x, True) ** 2)))(w))
    rel = np.linalg.norm(gq - gd) / np.linalg.norm(gd)
    assert rel < 0.05, rel

    # double ledger: wire vs dense-twin bytes off ONE trace
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    orig = cq_mod.comm_metrics
    cq_mod.comm_metrics = cm
    try:
        jax.eval_shape(lambda w, x: run(w, x, True), w, x)
    finally:
        cq_mod.comm_metrics = orig
    import json as _json
    metrics = _json.loads(reg.statz_json())["metrics"]

    def fam(name):
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return sum(x for x in v.values() if isinstance(x, (int, float)))
        return v or 0

    wire = fam("ds_comm_q_ppermute_bytes_total")
    dense = fam("ds_comm_q_ppermute_dense_bytes_total")
    assert dense > 0 and wire > 0
    assert dense >= 2 * wire, (wire, dense)
