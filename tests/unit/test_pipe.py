"""Pipeline-parallel tests (reference analog: tests/unit/runtime/pipe/,
SURVEY.md §4): parity of the SPMD pipeline against sequential execution,
and end-to-end training of the built-in model over a pp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.runtime.pipe import (LayerSpec, PipelineModule, spmd_pipeline)


class TanhLayer:
    def __init__(self, dim):
        self.dim = dim

    def init(self, rng, x):
        return {"w": jax.random.normal(rng, (self.dim, self.dim)) * 0.3}

    def apply(self, params, x):
        return jnp.tanh(x @ params["w"])


def test_spmd_pipeline_matches_sequential(devices, rng):
    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    L, D, B, M = 8, 16, 8, 4
    w = jax.random.normal(rng, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    def stage_fn(wl, xmb, _scan, *bcast):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        y, _ = jax.lax.scan(body, xmb, wl)
        return y, jnp.zeros((), jnp.float32)

    def sequential(w, x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    y, aux = jax.jit(lambda w, x: spmd_pipeline(stage_fn, w, x, mesh,
                                                num_microbatches=M))(w, x)
    ref = sequential(w, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # gradients through the pipeline == sequential gradients
    gp = jax.jit(jax.grad(lambda w: jnp.sum(
        spmd_pipeline(stage_fn, w, x, mesh, num_microbatches=M)[0] ** 2)))(w)
    gs = jax.jit(jax.grad(lambda w: jnp.sum(sequential(w, x) ** 2)))(w)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), rtol=1e-4, atol=1e-5)


def test_pipeline_module_api(devices, rng):
    mesh = build_mesh(fsdp=2, pp=4, devices=devices)
    set_global_mesh(mesh)
    D = 16
    module = PipelineModule([LayerSpec(TanhLayer, D) for _ in range(8)], mesh=mesh)
    x = jax.random.normal(rng, (8, D))
    params = module.init(rng, x)
    assert jax.tree.leaves(params)[0].shape[0] == 8  # stacked layer dim
    y = jax.jit(module.apply)(params, x)
    xs = x
    for i in range(8):
        xs = jnp.tanh(xs @ jax.tree.map(lambda a: a[i], params)["w"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(xs), rtol=1e-5, atol=1e-5)


def test_model_trains_on_pp_mesh(devices, rng):
    """Llama-family model end-to-end on pp=2 × fsdp=2 × tp=2."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(pp=2, fsdp=2, tp=2, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=4, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256)
    ds_config = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
                 "zero_optimization": {"stage": 1},
                 "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                 "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config, mesh=mesh)
    toks = jax.random.randint(rng, (8, 64), 0, 256)
    losses = []
    for _ in range(4):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pp_forward_matches_no_pp(devices, rng):
    """Same params, same tokens: pipelined forward == unpipelined forward."""
    from deepspeed_tpu.models import causal_lm

    toks = jax.random.randint(rng, (4, 32), 0, 128)
    kw = dict(num_layers=4, hidden_size=32, intermediate_size=64, num_heads=2,
              num_kv_heads=2, vocab_size=128, remat=False)

    mesh1 = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh1)
    m1 = causal_lm("llama-tiny", mesh=mesh1, **kw)
    params = m1.init(rng, toks)
    ref = jax.jit(m1.apply)(params, toks)

    mesh2 = build_mesh(pp=4, fsdp=2, devices=devices)
    set_global_mesh(mesh2)
    m2 = causal_lm("llama-tiny", mesh=mesh2, **kw)
    out = jax.jit(m2.apply)(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pp_loss_matches_no_pp(devices, rng):
    """Loss-in-pipeline (scalar reduction on the last stage) must equal the
    unpipelined loss — and the pipelined program must NOT materialize the
    replicated [B, S, D] hidden buffer (VERDICT r2 weak #5)."""
    from deepspeed_tpu.models import causal_lm

    toks = jax.random.randint(rng, (8, 32), 0, 256)
    kw = dict(num_layers=4, hidden_size=64, intermediate_size=128,
              num_heads=4, num_kv_heads=2, vocab_size=256, remat=False,
              ce_chunk=0)
    mesh_pp = build_mesh(pp=2, fsdp=2, tp=2, devices=devices)
    set_global_mesh(mesh_pp)
    model_pp = causal_lm("llama-tiny", mesh=mesh_pp, **kw)
    params = model_pp.init(jax.random.PRNGKey(3), toks)
    loss_pp = jax.jit(lambda p: model_pp.apply(p, toks, labels=toks))(params)

    mesh1 = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh1)
    model1 = causal_lm("llama-tiny", mesh=mesh1, **kw)
    loss1 = jax.jit(lambda p: model1.apply(p, toks, labels=toks))(params)
    np.testing.assert_allclose(float(loss_pp), float(loss1), rtol=2e-5)
