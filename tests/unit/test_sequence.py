"""Sequence-parallel tests: Ulysses + ring attention parity vs the dense
reference (reference analog: unit tests for deepspeed/sequence, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.ops.pallas import mha_reference
from deepspeed_tpu.sequence import (DistributedAttention, ring_attention,
                                    ulysses_attention)


@pytest.fixture()
def qkv(rng):
    B, H, S, D = 2, 4, 64, 16
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, H, S, D))
    k = jax.random.normal(kk, (B, H, S, D))
    v = jax.random.normal(kv, (B, H, S, D))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_parity(devices, qkv, causal):
    mesh = build_mesh(dp=2, sp=4, devices=devices)
    set_global_mesh(mesh)
    q, k, v = qkv
    ref = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_parity(devices, qkv, causal):
    mesh = build_mesh(dp=2, sp=4, devices=devices)
    set_global_mesh(mesh)
    q, k, v = qkv
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_grad_parity(devices, qkv):
    """The ring is a lax.scan — backward must match dense attention grads."""
    mesh = build_mesh(sp=4, fsdp=2, devices=devices)
    set_global_mesh(mesh)
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True).astype(jnp.float32) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_distributed_attention_api(devices, qkv):
    """Reference-parity class wrapper drives any local attention callable."""
    mesh = build_mesh(dp=2, sp=4, devices=devices)
    set_global_mesh(mesh)
    q, k, v = qkv
    import functools
    dist_attn = DistributedAttention(
        functools.partial(mha_reference, causal=True), mesh)
    out = dist_attn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sp_mode", ["ulysses", "ring"])
def test_model_trains_on_sp_mesh(devices, rng, sp_mode):
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(fsdp=2, sp=4, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, sp_mode=sp_mode)
    ds_config = {"train_batch_size": 4, "gradient_accumulation_steps": 1,
                 "zero_optimization": {"stage": 2},
                 "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                 "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config, mesh=mesh)
    toks = jax.random.randint(rng, (4, 64), 0, 256)
    losses = []
    for _ in range(4):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_ring_attention_residual_memory(devices, rng):
    """VERDICT r2 item 10 done-criterion: backward residuals must be O(S/P)
    — the custom VJP re-runs the ring instead of letting scan save every
    visiting KV chunk (which would add ~2x the input bytes again)."""
    from jax._src.ad_checkpoint import saved_residuals

    from deepspeed_tpu.comm.mesh import build_mesh
    from deepspeed_tpu.sequence.layer import ring_attention

    mesh = build_mesh(sp=4, fsdp=2, devices=devices)
    B, H, S, D = 2, 2, 64, 8
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))

    def f(q, k, v):
        return ring_attention(q, k, v, mesh).astype(jnp.float32).sum()

    res = saved_residuals(f, q, k, v)
    res_bytes = sum(int(np.prod(aval.shape)) * aval.dtype.itemsize
                    for aval, _ in res)
    base = 3 * B * H * S * D * 4          # q, k, v inputs
    out_lse = B * H * S * D * 4 + B * H * S * 4
    # old scan-residual version saved every visited KV chunk (~+2x inputs);
    # the custom VJP saves only inputs + out + lse (+ small scalars)
    assert res_bytes <= base + out_lse + 4096, \
        (res_bytes, base + out_lse)
