"""Comm façade + mesh tests on the 8-device virtual CPU mesh (SURVEY.md §4
implication (a): single-process multi-device harness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from deepspeed_tpu import comm
from deepspeed_tpu.comm.mesh import build_mesh


class TestMeshBuild:
    def test_default_fsdp_absorbs(self, devices):
        mesh = build_mesh(devices=devices)
        assert mesh.shape["fsdp"] == 8
        assert mesh.shape["dp"] == 1

    def test_explicit_axes(self, devices):
        mesh = build_mesh(dp=2, fsdp=2, tp=2, devices=devices)
        assert mesh.shape["dp"] == 2 and mesh.shape["fsdp"] == 2 and mesh.shape["tp"] == 2

    def test_infer_dp_from_fsdp(self, devices):
        mesh = build_mesh(fsdp=4, devices=devices)
        assert mesh.shape["dp"] == 2 and mesh.shape["fsdp"] == 4

    def test_bad_factorization(self, devices):
        with pytest.raises(ValueError):
            build_mesh(tp=3, devices=devices)

    def test_world_sizes(self, devices):
        from deepspeed_tpu.comm import mesh as M

        mesh = build_mesh(dp=2, fsdp=2, tp=2, devices=devices)
        assert M.get_data_parallel_world_size(mesh) == 4
        assert M.get_model_parallel_world_size(mesh) == 2


class TestCollectives:
    def test_all_reduce_sum(self, mesh8):
        @jax.jit
        def f(x):
            def body(x):
                return comm.all_reduce(x, axis="fsdp", op="sum")

            return shard_map(body, mesh=mesh8, in_specs=P("fsdp"), out_specs=P())(x)

        x = jnp.arange(8.0)
        out = f(x)
        np.testing.assert_allclose(out, np.full((1,), 28.0))

    def test_all_gather(self, mesh8):
        def body(x):
            return comm.all_gather(x, axis="fsdp", gather_dim=0)

        x = jnp.arange(8.0)
        out = shard_map(body, mesh=mesh8, in_specs=P("fsdp"), out_specs=P("fsdp"))(x)
        # each shard gathers the full array; out is [8*8] tiled
        assert out.shape == (64,)

    def test_reduce_scatter(self, mesh8):
        def body(x):
            return comm.reduce_scatter(x, axis="fsdp", scatter_dim=0)

        x = jnp.ones((8, 8))
        out = shard_map(body, mesh=mesh8, in_specs=P(None, None), out_specs=P("fsdp", None))(x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 8), 8.0))

    def test_all_to_all(self, mesh8):
        def body(x):
            return comm.all_to_all_single(x, axis="fsdp", split_dim=1, concat_dim=0)

        # Resharding flip dim0->dim1 (the Ulysses pattern): content unchanged.
        x = jnp.arange(64.0).reshape(8, 8)
        out = shard_map(body, mesh=mesh8, in_specs=P("fsdp", None), out_specs=P(None, "fsdp"))(x)
        assert out.shape == (8, 8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_ppermute_ring(self, mesh8):
        n = 8
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(x):
            return comm.ppermute(x, axis="fsdp", perm=perm)

        x = jnp.arange(8.0)
        out = shard_map(body, mesh=mesh8, in_specs=P("fsdp"), out_specs=P("fsdp"))(x)
        np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


class TestCommsLogger:
    def test_records_trace_time(self, mesh8):
        comm.comms_logger.configure(enabled=True)
        comm.comms_logger.reset()

        def body(x):
            return comm.all_reduce(x, axis="fsdp")

        x = jnp.ones((8, 4))
        shard_map(body, mesh=mesh8, in_specs=P("fsdp", None), out_specs=P(None, None))(x)
        assert any(k.startswith("all_reduce") for k in comm.comms_logger.counts)
        summary = comm.log_summary()
        assert "all_reduce" in summary
        comm.comms_logger.configure(enabled=False)


class TestControlPlane:
    def test_barrier_single_process(self):
        comm.barrier()  # no-op single process

    def test_broadcast_identity(self):
        x = jnp.ones((3,))
        np.testing.assert_allclose(comm.broadcast(x, src=0), x)

    def test_rank_world(self):
        assert comm.get_rank() == 0
        assert comm.get_world_size() == 8
        assert comm.get_local_rank() == 0


def test_new_group_subset_allreduce(devices):
    """Non-mesh-aligned device subsets via comm.new_group (reference
    dist.new_group; VERDICT r2 weak #7)."""
    from deepspeed_tpu import comm

    g = comm.new_group([1, 3, 5])
    assert g.size() == 3
    out = g.all_reduce([jnp.asarray(1.0), jnp.asarray(2.0), jnp.asarray(3.0)])
    assert float(out) == 6.0
    with pytest.raises(ValueError):
        comm.new_group([0, 99])
    with pytest.raises(ValueError):
        comm.new_group([0, -1])
    with pytest.raises(ValueError):
        g.all_reduce([jnp.asarray(1.0)])  # wrong member count


def test_group_aware_rank_and_world(devices):
    """get_rank/get_world_size honor group= (VERDICT r3 weak #7: previously
    accepted and ignored)."""
    from deepspeed_tpu import comm

    g = comm.new_group([0, 2, 5])
    assert comm.get_world_size(group=g) == 3
    assert comm.get_rank(group=g) == 0       # process 0 is member index 0
    g2 = comm.new_group([1, 3])
    assert comm.get_world_size(group=g2) == 2
    assert comm.get_rank(group=g2) == -1     # not a member (torch semantics)
    # no group: unchanged world semantics
    assert comm.get_world_size() == 8


def test_two_process_group_allreduce(tmp_path):
    """Eager control-plane subset reduce on real process boundaries: each of
    2 processes contributes its value; the member subset is reduced."""
    import os
    import socket
    import subprocess
    import sys
    import textwrap

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    script = tmp_path / "group_stub.py"
    script.write_text(textwrap.dedent("""\
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["DS_ACCELERATOR"] = "cpu"
        os.environ.pop("XLA_FLAGS", None)
        sys.path.insert(0, %r)
        from deepspeed_tpu import comm
        comm.init_distributed()
        import jax
        rank = jax.process_index()
        g = comm.new_group([0, 1], kind="process")
        total = g.all_reduce_across_processes(float(rank + 1))
        assert float(total) == 3.0, total
        g1 = comm.new_group([1], kind="process")
        only1 = g1.all_reduce_across_processes(float(rank + 1))
        assert float(only1) == 2.0, only1
        assert comm.get_rank(group=g) == rank
        assert comm.get_world_size(group=g) == 2
        print(f"GROUP OK rank={rank}")
        """) % repo)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith(("PALLAS_AXON", "AXON_"))}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--num_procs", "2", "--master_port", str(port), "--no_local_rank",
         str(script)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "GROUP OK rank=0" in proc.stdout
    assert "GROUP OK rank=1" in proc.stdout
