"""Elasticity tests (reference: tests/unit/elasticity/, SURVEY.md §5.3)."""

import pytest

from deepspeed_tpu.elasticity import (ElasticityConfigError,
                                      ElasticityIncompatibleWorldSize,
                                      compute_elastic_config, get_valid_gpus)

BASE = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 2000,
        "micro_batch_sizes": [2, 4, 6],
        "min_gpus": 1,
        "max_gpus": 10000,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_valid_gpus_basic():
    # batch 24, micro 2 -> accum*g divides 12; micro 4 -> 6; micro 6 -> 4
    gpus = get_valid_gpus(24, [2, 4, 6], 1, 100)
    assert 1 in gpus and 2 in gpus and 12 in gpus
    assert all(24 % g == 0 or any(24 % (m * g) == 0 for m in (2, 4, 6))
               for g in gpus)


def test_compute_elastic_config():
    final_batch, valid_gpus = compute_elastic_config(BASE)
    assert final_batch <= 2000
    assert len(valid_gpus) > 1
    # batch invariance: every valid gpu count evenly factors the batch
    for g in valid_gpus:
        assert any(final_batch % (m * g) == 0
                   for m in BASE["elasticity"]["micro_batch_sizes"])


def test_world_size_validation():
    final_batch, valid_gpus, micro = compute_elastic_config(
        BASE, world_size=valid_world(), return_microbatch=True)
    assert micro in BASE["elasticity"]["micro_batch_sizes"]
    assert final_batch % (micro * valid_world()) == 0


def valid_world():
    _, valid_gpus = compute_elastic_config(BASE)
    return valid_gpus[0]


def test_incompatible_world_size():
    cfg = {"elasticity": dict(BASE["elasticity"], micro_batch_sizes=[8],
                              max_train_batch_size=64)}
    _, valid = compute_elastic_config(cfg)
    bad = max(valid) + 1
    while bad in valid:
        bad += 1
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(cfg, world_size=bad)


def test_missing_section():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({})


def test_bad_version():
    cfg = {"elasticity": dict(BASE["elasticity"], version=9.9)}
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg)


def test_bad_micro_batches():
    cfg = {"elasticity": dict(BASE["elasticity"], micro_batch_sizes=[0, -2])}
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg)
