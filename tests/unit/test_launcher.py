"""Launcher stack tests (reference: tests/unit/launcher/, SURVEY.md §4).

Covers hostfile parsing, include/exclude filters, the per-host agent's env
contract + fail-fast supervision, and an end-to-end CLI run where two local
processes both pass ``comm.init_distributed`` (the VERDICT r2 done-criterion).
"""

import os
import socket
import subprocess
import sys
import textwrap
from collections import OrderedDict

import pytest

from deepspeed_tpu.launcher import launch as launch_mod
from deepspeed_tpu.launcher import runner as runner_mod


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(textwrap.dedent(text))
    return str(p)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# hostfile + filters
# ---------------------------------------------------------------------------

def test_fetch_hostfile(tmp_path):
    hf = _write(tmp_path, "hostfile", """\
        # comment
        worker-0 slots=4
        worker-1 slots=2
        """)
    pool = runner_mod.fetch_hostfile(hf)
    assert pool == OrderedDict([("worker-0", 4), ("worker-1", 2)])


def test_fetch_hostfile_malformed(tmp_path):
    hf = _write(tmp_path, "hostfile", "worker-0 gpus=4\n")
    with pytest.raises(ValueError):
        runner_mod.fetch_hostfile(hf)


def test_fetch_hostfile_missing():
    assert runner_mod.fetch_hostfile("/nonexistent/hostfile") == OrderedDict()


def test_include_filter():
    pool = OrderedDict([("w0", 4), ("w1", 4)])
    active = runner_mod.parse_inclusion_exclusion(pool, "w1:0,2", "")
    assert active == OrderedDict([("w1", [0, 2])])


def test_include_whole_host():
    pool = OrderedDict([("w0", 2), ("w1", 2)])
    active = runner_mod.parse_inclusion_exclusion(pool, "w0", "")
    assert active == OrderedDict([("w0", [0, 1])])


def test_exclude_filter():
    pool = OrderedDict([("w0", 2), ("w1", 2)])
    active = runner_mod.parse_inclusion_exclusion(pool, "", "w0:1@w1")
    assert active == OrderedDict([("w0", [0])])


def test_include_exclude_mutually_exclusive():
    pool = OrderedDict([("w0", 2)])
    with pytest.raises(ValueError):
        runner_mod.parse_inclusion_exclusion(pool, "w0", "w0")


def test_include_unknown_host():
    pool = OrderedDict([("w0", 2)])
    with pytest.raises(ValueError):
        runner_mod.parse_inclusion_exclusion(pool, "w9", "")


def test_world_info_roundtrip():
    active = OrderedDict([("a", [0, 1]), ("b", [0])])
    assert launch_mod.decode_world_info(runner_mod.encode_world_info(active)) == active


# ---------------------------------------------------------------------------
# per-host agent: env contract + fail-fast
# ---------------------------------------------------------------------------

def test_agent_env_contract(tmp_path):
    script = _write(tmp_path, "child.py", """\
        import json, os, sys
        out = {k: os.environ.get(k) for k in
               ("RANK", "LOCAL_RANK", "WORLD_SIZE", "COORDINATOR_ADDRESS")}
        out["argv"] = sys.argv[1:]
        with open(os.path.join(os.path.dirname(__file__),
                               f"env_{os.environ['RANK']}.json"), "w") as fh:
            json.dump(out, fh)
        """)
    world = runner_mod.encode_world_info(OrderedDict([("localhost", [0, 1])]))
    rc = launch_mod.main(["--world_info", world, "--node_rank", "0",
                          "--master_addr", "127.0.0.1", "--master_port", "29511",
                          script, "--flag", "x"])
    assert rc == 0
    import json

    for rank in (0, 1):
        with open(tmp_path / f"env_{rank}.json") as fh:
            env = json.load(fh)
        assert env["RANK"] == str(rank)
        assert env["LOCAL_RANK"] == str(rank)
        assert env["WORLD_SIZE"] == "2"
        assert env["COORDINATOR_ADDRESS"] == "127.0.0.1:29511"
        assert env["argv"] == [f"--local_rank={rank}", "--flag", "x"]


def test_agent_fail_fast(tmp_path):
    script = _write(tmp_path, "child.py", """\
        import os, sys, time
        if os.environ["RANK"] == "1":
            sys.exit(3)
        time.sleep(60)  # rank 0 hangs; the agent must kill it when rank 1 dies
        """)
    world = runner_mod.encode_world_info(OrderedDict([("localhost", [0, 1])]))
    import time

    t0 = time.time()
    rc = launch_mod.main(["--world_info", world, "--node_rank", "0",
                          "--master_addr", "127.0.0.1", "--master_port", "29512",
                          "--no_local_rank", script])
    assert rc == 3
    assert time.time() - t0 < 30, "fail-fast should not wait for the sleeper"


def test_agent_node_rank_offset(tmp_path):
    script = _write(tmp_path, "child.py", """\
        import os
        with open(os.path.join(os.path.dirname(__file__),
                               f"rank_{os.environ['RANK']}"), "w") as fh:
            fh.write(os.environ["LOCAL_RANK"])
        """)
    world = runner_mod.encode_world_info(
        OrderedDict([("hostA", [0, 1]), ("hostB", [0])]))
    rc = launch_mod.main(["--world_info", world, "--node_rank", "1",
                          "--master_addr", "127.0.0.1", "--master_port", "29513",
                          "--no_local_rank", script])
    assert rc == 0
    # node 1 hosts global rank 2 (offset = 2 slots on hostA), local rank 0
    assert (tmp_path / "rank_2").read_text() == "0"
    assert not (tmp_path / "rank_0").exists()


# ---------------------------------------------------------------------------
# end-to-end: CLI -> agent -> 2 processes -> init_distributed
# ---------------------------------------------------------------------------

def test_cli_two_process_init_distributed(tmp_path):
    """The VERDICT done-criterion: the CLI spawns 2 local processes that BOTH
    bootstrap jax.distributed through comm.init_distributed and agree on
    process_count == 2."""
    port = _free_port()
    script = _write(tmp_path, "train_stub.py", """\
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["DS_ACCELERATOR"] = "cpu"
        os.environ.pop("XLA_FLAGS", None)  # no virtual 8-device mesh here
        sys.path.insert(0, %r)
        from deepspeed_tpu import comm
        comm.init_distributed()
        import jax
        assert jax.process_count() == 2, jax.process_count()
        assert int(os.environ["RANK"]) == jax.process_index()
        comm.barrier()
        print(f"OK rank={jax.process_index()} world={jax.device_count()}")
        """ % os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    # Strip the TPU-tunnel plugin env: its sitecustomize initializes the XLA
    # backend at interpreter startup, which would block jax.distributed in
    # the children (backend must init AFTER distributed bootstrap).
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith(("PALLAS_AXON", "AXON_"))}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--num_procs", "2", "--master_port", str(port), "--no_local_rank",
         script],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env=env, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "OK rank=0" in proc.stdout
    assert "OK rank=1" in proc.stdout


def test_env_report_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.env_report"],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env={**os.environ}, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "deepspeed_tpu C++/Pallas op report" in proc.stdout
    assert "native.cpu_adam" in proc.stdout


def test_cli_two_process_sharded_checkpoint(tmp_path):
    """Multi-host checkpoint validation: 2 real processes save a sharded
    checkpoint (each writes ONLY its shard + index) and reload it — the
    no-full-gather contract exercised with actual process boundaries."""
    port = _free_port()
    ckdir = tmp_path / "ck"
    ckdir.mkdir()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    script = _write(tmp_path, "ck_stub.py", """\
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["DS_ACCELERATOR"] = "cpu"
        os.environ.pop("XLA_FLAGS", None)
        sys.path.insert(0, %r)
        from deepspeed_tpu import comm
        comm.init_distributed()
        import jax, numpy as np
        assert jax.process_count() == 2
        import deepspeed_tpu
        from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
        sys.path.insert(0, os.path.join(%r, "tests"))
        from tests.unit.simple_model import SimpleModel, random_dataset
        mesh = build_mesh(fsdp=2, devices=jax.devices())
        set_global_mesh(mesh)
        x, y = random_dataset(n=8)
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 1}, "steps_per_print": 10**9}
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16), config=cfg, mesh=mesh,
            rng=jax.random.PRNGKey(0))
        # each process supplies its local half of the global batch
        lo = jax.process_index() * 4
        eng.forward((x[lo:lo+4], y[lo:lo+4]))
        eng.step()
        eng.save_checkpoint(%r, tag="t")
        comm.barrier()
        ckpt = os.path.join(%r, "t", "model_states")
        mine = f"shard_p{jax.process_index()}.bin"
        assert os.path.exists(os.path.join(ckpt, mine)), mine
        names = sorted(os.listdir(ckpt))
        assert "shard_p0.bin" in names and "shard_p1.bin" in names, names
        eng.load_checkpoint(%r, tag="t")
        loss = eng.forward((x[lo:lo+4], y[lo:lo+4]))
        print(f"CKPT OK rank={jax.process_index()} loss={float(loss):.4f}")
        """ % (repo, repo, str(ckdir), str(ckdir), str(ckdir)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith(("PALLAS_AXON", "AXON_"))}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--num_procs", "2", "--master_port", str(port), "--no_local_rank",
         script],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "CKPT OK rank=0" in proc.stdout
    assert "CKPT OK rank=1" in proc.stdout
