"""Perf regression ledger (tools/perf_ledger.py): the jax-free tool
selftest wired tier-1 (the same pattern as the other operator tools),
the committed-trajectory gate, and a seeded 20% tokens/s regression
fixture that MUST fail ``--check`` loudly."""

import json
import os
import subprocess
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")
_REPO = os.path.abspath(os.path.join(_TOOLS, ".."))


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_perf_ledger_selftest():
    """--selftest: clean trajectory passes, the seeded regression
    fixture fails with the offending metric named, loose tolerances
    wave it through, a truncated block is reported as a gap."""
    ledger = _tool("perf_ledger")
    assert ledger.main(["perf_ledger", "--selftest"]) == 0


def test_perf_ledger_runs_without_jax():
    """Runtime half of the no-jax contract (the static half is dslint
    DSL003's import-graph closure, which now covers perf_ledger.py):
    the selftest runs in a fresh interpreter with no jax import."""
    script = os.path.join(_TOOLS, "perf_ledger.py")
    proc = subprocess.run(
        [sys.executable, script, "--selftest"], capture_output=True,
        text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert "perf_ledger selftest: OK" in proc.stdout


def test_committed_trajectory_passes_check():
    """make perf-diff's exact invocation over the repo's committed
    BENCH_*/MULTICHIP_* ledgers exits 0 — the gate a regression rung
    would trip."""
    ledger = _tool("perf_ledger")
    assert ledger.main(["perf_ledger", "--check",
                        f"--dir={_REPO}"]) == 0
    traj = ledger.load_trajectory(_REPO)
    assert traj["runs"], "committed ledgers went missing"
    # the BENCH_r05 truncated tail is a visible gap, never silent
    assert any("BENCH_r05" in g for g in traj["gaps"])


def test_seeded_regression_fails_check(tmp_path, capsys):
    """A 20% tokens/s drop at the trajectory tip exits nonzero and
    names the block + metric; direction-aware: the same relative move
    on a latency metric is flagged as a rise, and an improvement on
    either axis never fires."""
    ledger = _tool("perf_ledger")

    def rec(run, tok_s, p99):
        return {"metric": "demo_train_tokens_per_sec_per_chip",
                "value": tok_s, "unit": "tokens/s",
                "detail": {"serving_metrics": {"p99_latency_s": p99}}}

    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": rec("r01", 100.0, 0.20)}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": rec("r02", 80.0, 0.20)}))      # -20% tokens/s
    rc = ledger.main(["perf_ledger", "--check", f"--dir={tmp_path}"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out
    assert "demo_train_tokens_per_sec_per_chip" in out
    # improvements never fire: faster tip, lower latency
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": rec("r03", 120.0, 0.10)}))
    assert ledger.main(["perf_ledger", "--check",
                        f"--dir={tmp_path}"]) == 0
    # latency rising 20% beyond tolerance fires on the LOWER direction
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"parsed": rec("r04", 120.0, 0.15)}))
    rc = ledger.main(["perf_ledger", "--check", f"--dir={tmp_path}"])
    out = capsys.readouterr().out
    assert rc == 1 and "p99_latency_s" in out
    # a per-metric tolerance override waves exactly that metric through
    assert ledger.main(["perf_ledger", "--check", f"--dir={tmp_path}",
                        "--tolerance=p99=1.0"]) == 0


def test_run_meta_env_drift_attribution(tmp_path):
    """A regression whose two trajectory points disagree on run_meta
    (jax version bump) carries env_changed naming the drifted key —
    and git_sha churn alone is never 'drift'."""
    ledger = _tool("perf_ledger")
    base = {"metric": "m_tokens_per_sec", "value": 100.0,
            "run_meta": {"schema_version": 1, "jax": "0.4.1",
                         "git_sha": "aaa111"}}
    tip = {"metric": "m_tokens_per_sec", "value": 70.0,
           "run_meta": {"schema_version": 1, "jax": "0.4.2",
                        "git_sha": "bbb222"}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({"parsed": base}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"parsed": tip}))
    traj = ledger.load_trajectory(str(tmp_path))
    findings = ledger.find_regressions(traj)
    assert len(findings) == 1
    assert findings[0]["env_changed"] == ["jax"]
