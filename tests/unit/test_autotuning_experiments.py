"""Launcher-driven autotuning experiments + cost-model tuner (the two
reference-fidelity slices the round-3 verdict listed under missing #9)."""

import json
import os
import sys
import textwrap

import pytest

from deepspeed_tpu.autotuning import CostModelTuner, ExperimentRunner

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_experiment_runner_fresh_process_per_trial(tmp_path):
    """Each trial runs the user script in its own process with the patched
    config; the best-throughput trial wins; failures don't kill the search."""
    script = tmp_path / "trial_stub.py"
    script.write_text(textwrap.dedent("""\
        import json, os, sys
        cfg = json.load(open(os.environ["DS_AUTOTUNE_CONFIG"]))
        micro = cfg["train_micro_batch_size_per_gpu"]
        stage = cfg["zero_optimization"]["stage"]
        if micro >= 8:
            print("RESOURCE_EXHAUSTED: pretend OOM", file=sys.stderr)
            sys.exit(1)  # simulated OOM at large micro
        # deterministic synthetic throughput: stage 1 slightly better
        tput = micro * 100 + (10 if stage == 1 else 0)
        json.dump({"throughput": tput, "step_s": 1.0 / tput, "pid": os.getpid()},
                  open(os.environ["DS_AUTOTUNE_RESULT"], "w"))
        """))
    base = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    runner = ExperimentRunner(
        str(script), base,
        tuning_space={"zero_optimization.stage": [0, 1],
                      "train_micro_batch_size_per_gpu": [2, 4, 8]},
        results_dir=str(tmp_path / "results"), max_trials=10,
        trial_timeout_s=60)
    best_cfg, results = runner.run()
    ok = [r for r in results if r["status"] == "ok"]
    assert ok, results
    # every successful trial ran in its own process
    assert len({r["pid"] for r in ok}) == len(ok)
    # micro=8 rungs pruned as OOM per branch
    assert any(r["status"] == "oom" for r in results)
    # best: stage 1 micro 4 (410)
    assert best_cfg["zero_optimization"]["stage"] == 1
    assert best_cfg["train_micro_batch_size_per_gpu"] == 4
    assert os.path.exists(tmp_path / "results" / "summary.json")


def test_cost_model_tuner_skips_mid_points():
    """With affine step time, the tuner measures 2 small micros per branch
    then jumps to the predicted best — mid points are never measured."""
    measured = []

    def measure(overrides):
        m = overrides["train_micro_batch_size_per_gpu"]
        measured.append(m)
        if m > 16:
            return {"status": "oom"}
        return {"status": "ok", "step_s": 0.01 + 0.002 * m}

    tuner = CostModelTuner(
        measure,
        tuning_space={"train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16, 32]})
    best, results = tuner.tune()
    assert best["train_micro_batch_size_per_gpu"] == 16, (best, measured)
    # fit points (1, 2), then the model proposes 32 (OOM) and 16 (ok):
    # micro=4 and micro=8 never measured
    assert 4 not in measured and 8 not in measured, measured
    assert measured[:2] == [1, 2]


def test_cost_model_tuner_handles_all_oom():
    best, results = CostModelTuner(
        lambda o: {"status": "oom"},
        tuning_space={"train_micro_batch_size_per_gpu": [1, 2, 4]}).tune()
    assert best is None


def test_cost_model_tuner_salvages_single_fit_point():
    """A branch where the second fit point OOMs still reports the working
    measurement instead of 'no successful measurement'."""
    def measure(overrides):
        m = overrides["train_micro_batch_size_per_gpu"]
        if m >= 2:
            return {"status": "oom"}
        return {"status": "ok", "step_s": 0.01}

    best, results = CostModelTuner(
        measure,
        tuning_space={"train_micro_batch_size_per_gpu": [1, 2, 4]}).tune()
    assert best is not None
    assert best["train_micro_batch_size_per_gpu"] == 1
