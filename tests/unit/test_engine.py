"""Engine end-to-end tests on the 8-device virtual CPU mesh.

Covers the reference test matrix shape (SURVEY.md §4): parametrize over
(zero stage, dtype); loss decreases; grad-accum equivalence; checkpoint
save/load round-trips including cross-stage loads; fp16 overflow skip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from tests.unit.simple_model import SimpleModel, random_dataset


def make_engine(ds_config, n=64, dim=8, out_dim=4, model=None, **kw):
    x, y = random_dataset(n=n, dim=dim, out_dim=out_dim)
    model = model or SimpleModel(hidden_dim=16)
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=model, config=ds_config, training_data=(x, y), **kw)
    return engine, loader


BASE = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}


class TestTrainLoop:
    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_loss_decreases(self, stage):
        cfg = {**BASE, "zero_optimization": {"stage": stage}}
        engine, loader = make_engine(cfg)
        it = iter(__import__("deepspeed_tpu").runtime.dataloader.RepeatingLoader(loader))
        losses = [float(engine.train_batch(it)) for _ in range(20)]
        assert losses[-1] < losses[0] * 0.9, f"stage {stage}: loss did not decrease: {losses}"

    def test_imperative_api(self):
        engine, loader = make_engine({**BASE, "gradient_accumulation_steps": 2})
        it = iter(loader)
        b1, b2 = next(it), next(it)
        l1 = engine.forward(b1)
        engine.backward(l1)
        assert not engine.is_gradient_accumulation_boundary()
        engine.step()  # no-op off boundary
        assert engine.global_steps == 0
        l2 = engine.forward(b2)
        engine.backward(l2)
        assert engine.is_gradient_accumulation_boundary()
        engine.step()
        assert engine.global_steps == 1
        assert engine.get_global_grad_norm() is not None

    def test_grad_accum_equivalence(self):
        """gas=2 with micro=1 must equal gas=1 with micro=2 after one update."""
        x, y = random_dataset(n=16)
        outs = {}
        for gas, micro in ((1, 2), (2, 1)):
            cfg = {"train_micro_batch_size_per_gpu": micro,
                   "gradient_accumulation_steps": gas,
                   "optimizer": {"type": "SGD", "params": {"lr": 0.1}}}
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=SimpleModel(hidden_dim=16), config=cfg,
                rng=jax.random.PRNGKey(7))
            # same global batch content in both runs
            world = 8
            per_micro = micro * world
            batches = [(x[i * per_micro:(i + 1) * per_micro],
                        y[i * per_micro:(i + 1) * per_micro]) for i in range(gas)]
            for b in batches:
                engine.forward(b)
            engine.step()
            outs[gas] = jax.device_get(engine.state.params)
        flat1 = jax.tree_util.tree_leaves(outs[1])
        flat2 = jax.tree_util.tree_leaves(outs[2])
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    def test_fused_train_step_matches_loop(self):
        """One-dispatch train_step (scan over microbatches + update in one
        XLA program) must produce the same params as the forward/step loop."""
        x, y = random_dataset(n=16)
        world = 8
        gas, micro = 2, 1
        cfg = {"train_micro_batch_size_per_gpu": micro,
               "gradient_accumulation_steps": gas,
               "optimizer": {"type": "SGD", "params": {"lr": 0.1}}}
        outs = {}
        for mode in ("loop", "fused"):
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=SimpleModel(hidden_dim=16), config=cfg,
                rng=jax.random.PRNGKey(7))
            per_micro = micro * world
            if mode == "loop":
                for i in range(gas):
                    engine.forward((x[i * per_micro:(i + 1) * per_micro],
                                    y[i * per_micro:(i + 1) * per_micro]))
                engine.step()
            else:
                stacked = (x[: gas * per_micro].reshape(gas, per_micro, -1),
                           y[: gas * per_micro].reshape(gas, per_micro, -1))
                engine.train_step(stacked)
                assert engine.global_steps == 1
            outs[mode] = jax.device_get(engine.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(outs["loop"]),
                        jax.tree_util.tree_leaves(outs["fused"])):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    def test_train_step_flat_batch_reshape(self):
        """train_step accepts [gas*micro, ...] leaves and restacks them."""
        x, y = random_dataset(n=16)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "gradient_accumulation_steps": 2,
               "optimizer": {"type": "SGD", "params": {"lr": 0.1}}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16), config=cfg, rng=jax.random.PRNGKey(7))
        loss = engine.train_step((x, y))
        assert np.isfinite(float(loss))
        assert engine.global_steps == 1

    def test_bf16(self):
        cfg = {**BASE, "bf16": {"enabled": True}}
        engine, loader = make_engine(cfg)
        it = iter(__import__("deepspeed_tpu").runtime.dataloader.RepeatingLoader(loader))
        losses = [float(engine.train_batch(it)) for _ in range(10)]
        assert losses[-1] < losses[0]

    def test_eval_mode(self):
        engine, loader = make_engine(BASE)
        it = iter(loader)
        loss = engine.eval_batch(it)
        assert np.isfinite(float(loss))
        assert engine.global_steps == 0


class TestZeroSharding:
    def test_stage3_params_sharded(self):
        cfg = {**BASE, "zero_optimization": {"stage": 3,
                                             "stage3_param_persistence_threshold": 0}}
        engine, loader = make_engine(cfg, dim=8, out_dim=8)
        engine.train_batch(iter(loader))
        specs = jax.tree_util.tree_leaves(
            engine._param_specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
        assert any(any(ax is not None for ax in s) for s in specs), "no param was sharded"

    def test_stage1_opt_sharded_params_replicated(self):
        cfg = {**BASE, "zero_optimization": {"stage": 1}}
        engine, loader = make_engine(cfg, dim=8, out_dim=8)
        engine.train_batch(iter(loader))
        pspecs = jax.tree_util.tree_leaves(
            engine._param_specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
        assert all(all(ax is None for ax in s) for s in pspecs)
        ospecs = jax.tree_util.tree_leaves(
            engine._opt_specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec))
        assert any(any(ax is not None for ax in s) for s in ospecs), "no opt state sharded"

    @pytest.mark.parametrize("save_stage,load_stage", [(0, 3), (3, 0), (2, 3)])
    def test_cross_stage_checkpoint(self, tmp_path, save_stage, load_stage):
        """Save under one ZeRO stage, load under another (SURVEY.md §4)."""
        cfg_s = {**BASE, "zero_optimization": {"stage": save_stage}}
        engine, loader = make_engine(cfg_s)
        engine.train_batch(iter(loader))
        engine.save_checkpoint(str(tmp_path))
        ref = jax.device_get(engine.state.params)

        cfg_l = {**BASE, "zero_optimization": {"stage": load_stage}}
        engine2, loader2 = make_engine(cfg_l)
        engine2.train_batch(iter(loader2))  # init state (different weights)
        engine2.load_checkpoint(str(tmp_path))
        got = jax.device_get(engine2.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(got)):
            np.testing.assert_allclose(a, b, rtol=1e-6)


class TestCheckpoint:
    def test_roundtrip_with_counters(self, tmp_path):
        engine, loader = make_engine(BASE)
        it = iter(__import__("deepspeed_tpu").runtime.dataloader.RepeatingLoader(loader))
        for _ in range(3):
            engine.train_batch(it)
        path = engine.save_checkpoint(str(tmp_path), client_state={"epoch": 5})
        assert "global_step3" in path

        engine2, _ = make_engine(BASE)
        engine2.train_batch(iter(loader))
        _, client = engine2.load_checkpoint(str(tmp_path))
        assert engine2.global_steps == 3
        assert client["epoch"] == 5

    def test_latest_file(self, tmp_path):
        engine, loader = make_engine(BASE)
        engine.train_batch(iter(loader))
        engine.save_checkpoint(str(tmp_path), tag="mytag")
        assert (tmp_path / "latest").read_text() == "mytag"

    def test_save_16bit_model(self, tmp_path):
        cfg = {**BASE, "bf16": {"enabled": True}}
        engine, loader = make_engine(cfg)
        engine.train_batch(iter(loader))
        p = engine.save_16bit_model(str(tmp_path))
        from deepspeed_tpu.runtime.checkpoint_engine import (ShardedCheckpointEngine,
                                                             is_sharded_checkpoint)
        assert p and is_sharded_checkpoint(str(tmp_path / "model_states_16bit"))
        flat = ShardedCheckpointEngine().load(p)
        # every non-integer leaf must have been cast to the compute dtype
        assert all(str(a.dtype) == "bfloat16" for a in flat.values()
                   if not np.issubdtype(np.asarray(a).dtype, np.integer))


class TestFP16:
    def test_dynamic_loss_scale_starts(self):
        cfg = {**BASE, "fp16": {"enabled": True, "initial_scale_power": 8}}
        engine, loader = make_engine(cfg)
        engine.train_batch(iter(loader))
        assert engine.loss_scale in (256.0, 512.0)

    def test_overflow_skips_step(self):
        cfg = {**BASE, "fp16": {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}}
        engine, loader = make_engine(cfg)
        it = iter(loader)
        engine.train_batch(it)
        params_before = jax.device_get(engine.state.params)
        # poison a batch -> non-finite grads -> step must be skipped + scale halved
        x = np.full((8, 8), np.inf, dtype=np.float32)
        y = np.zeros((8, 4), dtype=np.float32)
        engine.forward((x, y))
        scale_before = engine.loss_scale
        engine.step()
        assert engine.skipped_steps >= 1
        assert engine.loss_scale == scale_before / 2
        params_after = jax.device_get(engine.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(params_before),
                        jax.tree_util.tree_leaves(params_after)):
            np.testing.assert_array_equal(a, b)


class TestSchedulers:
    def test_warmup_lr_from_config(self):
        cfg = {**BASE,
               "scheduler": {"type": "WarmupLR",
                             "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                        "warmup_num_steps": 10}}}
        engine, loader = make_engine(cfg)
        it = iter(__import__("deepspeed_tpu").runtime.dataloader.RepeatingLoader(loader))
        engine.train_batch(it)
        lr1 = engine.get_lr()[0]
        for _ in range(5):
            engine.train_batch(it)
        lr2 = engine.get_lr()[0]
        assert lr2 > lr1
