"""Data analysis + curriculum sampling (SURVEY §2.1 "Data efficiency",
the data_sampling/ half the round-3 verdict flagged as missing)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (DataAnalyzer,
                                                 DeepSpeedDataSampler,
                                                 seqlen_metric)


def _dataset(n=64, seed=0):
    """Variable-length token samples: difficulty == length."""
    rng = np.random.RandomState(seed)
    return [np.arange(rng.randint(4, 4 + i % 32 + 1)) for i in range(n)]


def test_analyzer_map_reduce_multiworker(tmp_path):
    ds = _dataset(50)
    DataAnalyzer(ds, str(tmp_path), num_workers=3).run()
    import os

    s2m = np.load(os.path.join(tmp_path, "seqlen", "sample_to_metric.npy"))
    m2s = np.load(os.path.join(tmp_path, "seqlen", "metric_to_sample.npy"))
    assert len(s2m) == 50
    np.testing.assert_array_equal(s2m, [len(s) for s in ds])
    # sorted index really sorts by metric
    assert (np.diff(s2m[m2s]) >= 0).all()


def test_analyzer_reduce_detects_missing_worker(tmp_path):
    ds = _dataset(20)
    a = DataAnalyzer(ds, str(tmp_path), num_workers=2, worker_id=0)
    a.run_map()  # worker 1 never runs
    with pytest.raises(RuntimeError, match="worker 1 wrote no seqlen"):
        a.run_reduce()


def _sampler(tmp_path, n=64, **kw):
    ds = _dataset(n)
    DataAnalyzer(ds, str(tmp_path)).run()
    metrics = {"seqlen": {"index_path": str(tmp_path / "seqlen"),
                          "difficulty_type": "value",
                          "curriculum_type": "fixed_linear",
                          "min_difficulty": 8, "max_difficulty": 40,
                          "total_curriculum_step": 10,
                          "difficulty_step": 1}}
    return ds, DeepSpeedDataSampler(num_samples=n, global_batch_size=8,
                                    curriculum_metrics=metrics, **kw)


def test_sampler_respects_difficulty_ramp(tmp_path):
    ds, sampler = _sampler(tmp_path)
    early = sampler.sample_step(0)
    assert all(len(ds[int(i)]) <= 8 for i in early), \
        [len(ds[int(i)]) for i in early]
    late = sampler.sample_step(100)
    assert max(len(ds[int(i)]) for i in late) > 8


def test_sampler_deterministic_and_resumable(tmp_path):
    _, s1 = _sampler(tmp_path)
    seq1 = [s1.sample_step() for _ in range(5)]
    _, s2 = _sampler(tmp_path)
    s2.load_state_dict({"global_step": 3, "consumed_samples": 24,
                        "seed": 1234})
    seq2 = [s2.sample_step() for _ in range(2)]
    np.testing.assert_array_equal(seq1[3], seq2[0])
    np.testing.assert_array_equal(seq1[4], seq2[1])


def test_sampler_dp_ranks_partition_batch(tmp_path):
    _, s0 = _sampler(tmp_path, data_parallel_rank=0, data_parallel_size=2)
    _, s1 = _sampler(tmp_path, data_parallel_rank=1, data_parallel_size=2)
    a = s0.sample_step(5)
    b = s1.sample_step(5)
    assert len(a) == len(b) == 4  # 8 global / 2 ranks
    # same step -> same global picks, disjoint halves (pool >= batch here,
    # so choice(replace=False) guarantees distinct picks)
    assert not (set(map(int, a)) & set(map(int, b))), (a, b)
    _, s_full = _sampler(tmp_path, data_parallel_rank=0,
                         data_parallel_size=1)
    full = s_full.sample_step(5)
    np.testing.assert_array_equal(np.concatenate([a, b]), full)


def test_shuffle_epoch_traversal():
    """shuffle=True visits every admitted sample exactly once per epoch
    before any repeats (ADVICE r4: i.i.d. per-step choice had no epoch
    semantics), and reshuffles between epochs."""
    s = DeepSpeedDataSampler(num_samples=64, global_batch_size=8)
    epoch0 = np.concatenate([s.sample_step(t) for t in range(8)])
    assert sorted(map(int, epoch0)) == list(range(64))
    epoch1 = np.concatenate([s.sample_step(t) for t in range(8, 16)])
    assert sorted(map(int, epoch1)) == list(range(64))
    assert not np.array_equal(epoch0, epoch1), "epochs must reshuffle"


def test_percentile_difficulty(tmp_path):
    n = 64
    ds = _dataset(n)
    DataAnalyzer(ds, str(tmp_path)).run()
    metrics = {"seqlen": {"index_path": str(tmp_path / "seqlen"),
                          "difficulty_type": "percentile",
                          "curriculum_type": "fixed_linear",
                          "min_difficulty": 10, "max_difficulty": 100,
                          "total_curriculum_step": 10,
                          "difficulty_step": 1}}
    sampler = DeepSpeedDataSampler(num_samples=n, global_batch_size=8,
                                   curriculum_metrics=metrics)
    lens = sorted(len(s) for s in ds)
    cutoff = lens[max(0, int(np.ceil(n * 0.10)) - 1)]
    early = sampler.sample_step(0)
    assert all(len(ds[int(i)]) <= cutoff for i in early)
