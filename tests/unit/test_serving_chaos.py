"""Serving-fleet failure containment (ISSUE 13): overload shed +
deadline units, circuit breaker / retry budget / 429-backoff router
units, idempotent dispatch under injected socket deaths, drain racing a
kill, and THE chaos acceptance e2e — a 20-request trace through the
router over two live replicas under injected network faults and a
mid-trace replica kill + supervisor-style restart, with every non-shed
request answered exactly once and token-identical to ``generate()``.

The in-process "kill" is a serving-loop crash injected at a step
boundary (``chaos.crash_on_call``) — state-clean, so the in-process
revive (the supervisor's restart action) is legitimate; PROCESS-level
SIGKILL/wedge restarts are pinned by ``tools/serve_supervisor.py
--selftest`` (tests/unit/test_serve_supervisor.py) over real
subprocesses."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.metrics import MetricsRegistry
from deepspeed_tpu.serving import (Router, RouterServer, IterationScheduler,
                                   QueueFull, Request)
from deepspeed_tpu.testing.chaos import (ChaosProxy, crash_on_call,
                                         http_error_burst)

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "..", "tools")


def _tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# scheduler overload units (no model)
# ---------------------------------------------------------------------------

def _req(n_prompt=3, max_new=4, deadline=0.0):
    r = Request(prompt=np.arange(1, n_prompt + 1, dtype=np.int32),
                max_new_tokens=max_new)
    r.deadline = deadline
    return r


def test_scheduler_sheds_past_watermark():
    """Bounded admission queue: the submit that crosses max_queue_depth
    raises QueueFull carrying the configured Retry-After, and the shed
    counter moves; space freed by admission re-opens the queue."""
    reg = MetricsRegistry().enable()
    sched = IterationScheduler(2, registry=reg, max_queue_depth=2,
                               shed_retry_after_s=0.7)
    sched.submit(_req())
    sched.submit(_req())
    with pytest.raises(QueueFull) as ei:
        sched.submit(_req())
    assert ei.value.retry_after_s == 0.7
    assert reg.get("ds_serve_shed_total").value == 1
    assert sched.num_queued == 2
    sched.admit()                        # both take slots, queue empties
    sched.submit(_req())                 # accepted again
    assert reg.get("ds_serve_shed_total").value == 1


def test_scheduler_deadline_expires_queued_requests():
    """A request still QUEUED past its deadline is cancelled with reason
    ``deadline`` at the next admit — it never takes a slot; requests
    with live deadlines are untouched."""
    reg = MetricsRegistry().enable()
    sched = IterationScheduler(1, registry=reg)
    now = time.perf_counter()
    r1 = sched.submit(_req())                        # takes the one slot
    sched.admit()
    dead = sched.submit(_req(deadline=now - 1.0))    # already expired
    live = sched.submit(_req(deadline=now + 60.0))
    assert sched.admit() == []                       # slot busy; expiry ran
    assert dead.done and dead.finish_reason == "deadline"
    assert reg.get("ds_serve_deadline_expired_total").value == 1
    assert reg.get("ds_serve_finished_total",
                   labels={"reason": "deadline"}).value == 1
    assert not live.done and sched.num_queued == 1
    # expired requests are NOT in finished (never served here) — the
    # cancel contract; the slot then goes to the live request
    assert dead not in sched.finished
    sched.finish(r1)
    assert sched.admit() == [live]


# ---------------------------------------------------------------------------
# router hardening units (synthetic replicas — the tools/router fixture)
# ---------------------------------------------------------------------------

def test_breaker_trips_half_opens_and_heals():
    """Consecutive dispatch failures trip the replica's breaker (it is
    skipped while its /healthz still answers 200 — the sick-but-alive
    case); after the cooldown a single half-open probe heals it."""
    router_tool = _tool("router")
    a, b = router_tool._FakeReplica("a"), router_tool._FakeReplica("b")
    reg = MetricsRegistry().enable()
    router = Router([f"a={a.url}", f"b={b.url}"], registry=reg,
                    dispatch_rounds=4, retry_backoff=0.01,
                    breaker_threshold=2, breaker_cooldown=0.3,
                    breaker_cooldown_max=5.0)
    try:
        a.queue_depth = 5                 # b is the least-loaded target
        router.refresh()
        b.error_next = 10
        rb = router._by_name["b"]
        for _ in range(2):                # each dispatch: b 500s, a serves
            code, body = router.dispatch({"prompt": [1], "max_new_tokens": 2})
            assert code == 200 and body["replica"] == "a"
        assert rb.breaker_state(time.monotonic()) == "open"
        assert reg.get("ds_router_breaker_trips_total").value == 1
        assert reg.get("ds_router_breaker_open",
                       labels={"replica": "b"}).value == 1
        assert b.error_next == 8          # exactly 2 failures consumed
        # while open, b is skipped entirely (healthz still 200)
        router.refresh()
        assert rb.ready
        code, body = router.dispatch({"prompt": [2], "max_new_tokens": 2})
        assert code == 200 and body["replica"] == "a"
        assert b.error_next == 8
        # cooldown passes -> half-open -> one successful probe closes it
        b.error_next = 0
        time.sleep(0.35)
        code, body = router.dispatch({"prompt": [3], "max_new_tokens": 2})
        assert code == 200 and body["replica"] == "b"
        assert rb.breaker_state(time.monotonic()) == "closed"
        assert reg.get("ds_router_breaker_open",
                       labels={"replica": "b"}).value == 0
        # a failed probe re-trips with the cooldown DOUBLED
        b.error_next = 10
        code, _ = router.dispatch({"prompt": [4], "max_new_tokens": 2})
        assert code == 200                # served by a after b's failure
        code, _ = router.dispatch({"prompt": [5], "max_new_tokens": 2})
        time.sleep(0.35)                  # first cooldown: now half-open
        code, _ = router.dispatch({"prompt": [6], "max_new_tokens": 2})
        assert code == 200                # probe failed -> re-open
        assert rb.breaker_state(time.monotonic()) == "open"
        assert rb._cooldown == pytest.approx(0.6)
    finally:
        a.stop()
        b.stop()


def test_retry_budget_throttles_sick_fleet():
    """With every replica failing, retries stop when the token bucket
    runs dry — the router must not amplify a fleet-wide outage by
    dispatch_rounds x offered load."""
    router_tool = _tool("router")
    a, b = router_tool._FakeReplica("a"), router_tool._FakeReplica("b")
    reg = MetricsRegistry().enable()
    router = Router([f"a={a.url}", f"b={b.url}"], registry=reg,
                    dispatch_rounds=8, retry_backoff=0.01,
                    breaker_threshold=99, retry_budget_cap=2.0,
                    retry_budget_ratio=0.0)
    try:
        router.refresh()
        a.error_next = b.error_next = 100
        code, body = router.dispatch({"prompt": [1], "max_new_tokens": 2})
        assert code == 503
        assert "retry budget exhausted" in body["error"]
        # 1 first attempt + exactly 2 budgeted retries = 3 posts total
        assert (100 - a.error_next) + (100 - b.error_next) == 3
        assert reg.get("ds_router_retry_budget_exhausted_total").value >= 1
    finally:
        a.stop()
        b.stop()


def test_fleet_wide_shed_surfaces_429_with_retry_after():
    """429 is not a failure: shedding replicas keep membership and a
    closed breaker; when EVERY ready replica sheds, the client gets 429
    with the largest Retry-After (header included on the HTTP front)."""
    router_tool = _tool("router")
    a, b = router_tool._FakeReplica("a"), router_tool._FakeReplica("b")
    reg = MetricsRegistry().enable()
    router = Router([f"a={a.url}", f"b={b.url}"], registry=reg,
                    dispatch_rounds=4, retry_backoff=0.01)
    front = RouterServer(router).start()
    try:
        router.refresh()
        a.shed_next = b.shed_next = 5
        req = urllib.request.Request(
            front.url + "/generate",
            data=json.dumps({"prompt": [1], "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        body = json.load(ei.value)
        assert body["shed"] is True and body["retry_after_s"] > 0
        assert ei.value.headers["Retry-After"] is not None
        # 4 dispatch rounds, each answered by a shed (a, b, a, b)
        assert reg.get("ds_router_shed_429_total").value == 4
        # graceful degradation, not an outage: membership + breakers
        # untouched, and the fleet serves again the moment load drops
        for rep in router.replicas:
            assert rep.ready
            assert rep.breaker_state(time.monotonic()) == "closed"
        a.shed_next = b.shed_next = 0
        code, _ = router.dispatch({"prompt": [2], "max_new_tokens": 2})
        assert code == 200
    finally:
        front.stop()
        a.stop()
        b.stop()


def test_blackholed_healthz_drops_membership():
    """A black-holed replica socket (accepts, never answers) reads as
    unreachable on the bounded healthz poll — membership drops instead
    of the router hanging on it."""
    router_tool = _tool("router")
    a = router_tool._FakeReplica("a")
    proxy = ChaosProxy(int(a.url.rsplit(":", 1)[1])).start()
    try:
        router = Router([f"a={proxy.url}"],
                        registry=MetricsRegistry().enable(),
                        poll_timeout=0.3)
        router.refresh()
        assert router.replicas[0].ready
        proxy.mode = "blackhole"
        router.refresh()
        assert not router.replicas[0].ready
        assert "unreachable" in router.replicas[0].reason
        proxy.mode = "pass"
        router.refresh()
        assert router.replicas[0].ready
    finally:
        proxy.stop()
        a.stop()


# ---------------------------------------------------------------------------
# live fleet: two real replicas, a chaos proxy on replica 0, the router
# front — the acceptance surface
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet(devices):
    """(ref engine, [serve0, serve1], proxy, router, front, model,
    params): replica 0 is reached THROUGH the chaos proxy; both replicas
    run bounded admission queues (max_queue_depth) so overload sheds."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    replicas = []
    for _ in range(2):
        serve = deepspeed_tpu.init_serving(
            model, config={"dtype": "float32", "max_out_tokens": 64,
                           "kv_page_tokens": 16, "max_queue_depth": 4,
                           "shed_retry_after_s": 0.2},
            num_slots=2, prefill_chunk=8, decode_block_tokens=3,
            metrics_port=0, registry=MetricsRegistry().enable(),
            private_health=True, serve_loop=True)
        serve.set_params(params)
        replicas.append(serve)
    proxy = ChaosProxy(replicas[0].metrics_server.port).start()
    router = Router(
        [f"repl0={proxy.url}",
         f"repl1={replicas[1].metrics_server.url}"],
        registry=MetricsRegistry().enable(), dispatch_rounds=8,
        retry_backoff=0.02, poll_interval=0.05, poll_timeout=1.0,
        breaker_cooldown=0.3, request_timeout=120.0)
    router.refresh()
    front = RouterServer(router).start()
    yield ref, replicas, proxy, router, front, model, params
    front.stop()
    router.stop()
    proxy.stop()
    for s in replicas:
        s.close()


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def _quiesce(serve, timeout=30):
    """Wait until a replica has no occupied slots and no allocated
    pages (abort teardowns need live steps, so the loop must be up)."""
    deadline = time.monotonic() + timeout
    while (serve.scheduler.num_occupied or serve.pool.pages_used) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert serve.scheduler.num_occupied == 0
    assert serve.pool.pages_used == 0
    serve.pool.check_no_leak()


def _reset_fleet(replicas, proxy, router):
    proxy.mode = "pass"
    for s in replicas:
        if not s._loop_alive():
            s.start_loop()
        s.resume_admission()
    # a fresh traffic epoch: the previous test's chaos must not leak
    # through the shared router (a drained retry bucket / tripped
    # breaker would fail clients that never saw any fault)
    with router._lock:
        router._retry_tokens = router.retry_budget_cap
    for rep in router.replicas:
        rep.note_success()
    router.refresh()
    assert sum(r.ready for r in router.replicas) == 2, \
        [r.snapshot() for r in router.replicas]


def test_idempotent_duplicate_joins_inflight(fleet, rng):
    """Two concurrent dispatches carrying the same idempotency key
    produce ONE generation: the duplicate joins the in-flight original;
    a later replay of the key returns the stored result without
    re-generating."""
    _ref, replicas, proxy, router, _front, _m, _p = fleet
    _reset_fleet(replicas, proxy, router)
    serve = replicas[1]
    url = serve.metrics_server.url
    reg = serve._registry
    base_sub = reg.get("ds_serve_submitted_total").value
    prompt = np.asarray(jax.random.randint(rng, (9,), 0, 256)).tolist()
    payload = {"prompt": prompt, "max_new_tokens": 48,
               "idempotency_key": "dup-key-1"}
    results = [None, None]

    def post(i):
        results[i] = _post(url, payload)

    t0 = threading.Thread(target=post, args=(0,))
    t0.start()
    time.sleep(0.05)                      # the original is in flight
    t1 = threading.Thread(target=post, args=(1,))
    t1.start()
    t0.join(60)
    t1.join(60)
    assert results[0][0] == 200 and results[1][0] == 200
    assert results[0][1]["tokens"] == results[1][1]["tokens"]
    assert results[0][1]["request_id"] == results[1][1]["request_id"]
    assert reg.get("ds_serve_submitted_total").value == base_sub + 1
    assert reg.get("ds_serve_idem_hits_total").value >= 1
    # replay after finish: same answer, still no new generation
    code, body = _post(url, payload)
    assert code == 200 and body["tokens"] == results[0][1]["tokens"]
    assert reg.get("ds_serve_submitted_total").value == base_sub + 1


def test_idempotent_retry_after_delivered_socket_death(fleet, rng):
    """The router.py:321 double-generation hazard, closed: the proxy
    DELIVERS the request to replica 0 and kills the connection before
    the response (ambiguous socket death — the work happened).  The
    router's idempotent retry re-asks and JOINS/replays the original:
    client answered once, replica generated once."""
    _ref, replicas, proxy, router, _front, _m, _p = fleet
    _reset_fleet(replicas, proxy, router)
    serve = replicas[0]
    reg = serve._registry
    base_sub = reg.get("ds_serve_submitted_total").value
    # a PRIVATE proxy + single-replica router: the retry MUST return to
    # the same replica (the double-generation case), and no background
    # poll can eat the injected one-shot fault
    myproxy = ChaosProxy(serve.metrics_server.port).start()
    solo = Router([f"repl0={myproxy.url}"],
                  registry=MetricsRegistry().enable(),
                  dispatch_rounds=6, retry_backoff=0.05, poll_timeout=1.0)
    try:
        solo.refresh()
        prompt = np.asarray(jax.random.randint(rng, (7,), 0, 256)).tolist()
        myproxy.inject("deliver_then_reset")
        code, body = solo.dispatch({"prompt": prompt, "max_new_tokens": 6})
        assert code == 200, body
        assert myproxy.counts.get("deliver_then_reset") == 1
        # ONE generation despite two deliveries of the same payload
        assert reg.get("ds_serve_submitted_total").value == base_sub + 1
        assert reg.get("ds_serve_idem_hits_total").value >= 1
        assert solo.registry.get("ds_router_retries_total").value >= 1
    finally:
        myproxy.stop()


def test_real_replica_sheds_429_and_deadline_504(fleet, rng):
    """Deterministic overload on a 1-slot replica: the slot is held by a
    long request, the bounded queue fills, the next dispatch 429s with
    Retry-After; a queued request with a tiny service deadline 504s
    with deadline_expired (and never takes the slot)."""
    _ref, _replicas, _proxy, _router, _front, model, params = fleet
    serve = deepspeed_tpu.init_serving(
        model, config={"dtype": "float32", "max_out_tokens": 64,
                       "kv_page_tokens": 16, "max_queue_depth": 1,
                       "shed_retry_after_s": 0.4},
        num_slots=1, prefill_chunk=8, decode_block_tokens=2,
        metrics_port=0, registry=MetricsRegistry().enable(),
        private_health=True, serve_loop=True)
    serve.set_params(params)
    try:
        url = serve.metrics_server.url
        prompt = np.asarray(jax.random.randint(rng, (8,), 0, 256)).tolist()
        results = []

        def client(max_new):
            try:
                results.append(_post(url, {"prompt": prompt,
                                           "max_new_tokens": max_new}))
            except urllib.error.HTTPError as exc:
                results.append((exc.code, json.load(exc)))

        long_client = threading.Thread(target=client, args=(56,))
        long_client.start()
        deadline = time.monotonic() + 15
        while serve.scheduler.num_occupied == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert serve.scheduler.num_occupied == 1
        # fill the (depth-1) queue…
        q_client = threading.Thread(target=client, args=(2,))
        q_client.start()
        while serve.scheduler.num_queued == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        # …and the next dispatch sheds with the configured Retry-After
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url, {"prompt": prompt, "max_new_tokens": 2})
        assert ei.value.code == 429
        shed = json.load(ei.value)
        assert shed["shed"] is True
        assert shed["retry_after_s"] == pytest.approx(0.4)
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert serve._registry.get("ds_serve_shed_total").value >= 1
        long_client.join(60)
        q_client.join(60)
        assert all(code == 200 for code, _ in results), results
        # deadline: hold the slot again, then queue a doomed request
        results.clear()
        long_client = threading.Thread(target=client, args=(56,))
        long_client.start()
        deadline = time.monotonic() + 15
        while serve.scheduler.num_occupied == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url, {"prompt": prompt, "max_new_tokens": 2,
                        "deadline_s": 0.05})
        assert ei.value.code == 504
        body = json.load(ei.value)
        assert body["deadline_expired"] is True
        assert serve._registry.get(
            "ds_serve_deadline_expired_total").value >= 1
        assert serve._registry.get(
            "ds_serve_finished_total",
            labels={"reason": "deadline"}).value >= 1
        long_client.join(60)
        _quiesce(serve)
    finally:
        serve.close()


def test_injected_500s_trip_breaker_and_fleet_recovers(fleet, rng):
    """500s injected at replica 1's /generate seam (the engine itself is
    healthy, /healthz answers 200): the router's breaker trips, traffic
    flows to replica 0, and the half-open probe heals membership once
    the burst ends — zero client-visible failures throughout."""
    _ref, replicas, proxy, router, front, _m, _p = fleet
    _reset_fleet(replicas, proxy, router)
    serve = replicas[1]
    real = serve._http_generate
    wrapped, state = http_error_burst(real, 3, code=500)
    serve.metrics_server.set_generate_handler(wrapped)
    rb0 = router._by_name["repl0"]
    rb1 = router._by_name["repl1"]
    base_trips = router.registry.get("ds_router_breaker_trips_total").value
    try:
        # bias the pick toward repl1 so the injected seam actually fires
        # (equal scores tie-break to repl0 by name)
        rb0.queue_depth = 50.0
        prompt = np.asarray(jax.random.randint(rng, (6,), 0, 256)).tolist()
        for i in range(4):
            code, body = _post(front.url,
                               {"prompt": prompt, "max_new_tokens": 3})
            assert code == 200, body     # zero client-visible failures
        assert state["errors"] == 3      # the seam fired and drained
        assert router.registry.get("ds_router_retries_total").value >= 3
        assert router.registry.get(
            "ds_router_breaker_trips_total").value > base_trips
        # the burst is over: the half-open probe heals repl1
        time.sleep(0.35)
        code, body = _post(front.url,
                           {"prompt": prompt, "max_new_tokens": 3})
        assert code == 200 and body["replica"] == "repl1"
        assert rb1.breaker_state(time.monotonic()) == "closed"
    finally:
        rb0.queue_depth = 0.0
        serve.metrics_server.set_generate_handler(real)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_chaos_acceptance_e2e(fleet, rng):
    """THE acceptance e2e (ISSUE 13): a 20-request bimodal shared-prefix
    trace through the router front over two live replicas while the
    harness injects (a) an ambiguous delivered-then-reset socket death
    and connection refusals on replica 0's proxy, and (b) a mid-trace
    KILL of replica 1's serving loop, revived by a supervisor-style
    watcher (restart + resume — the in-process analog of
    serve_supervisor's process restart).  Every non-shed request is
    answered exactly once and token-identical to generate(); shed
    requests are cleanly 429'd with Retry-After; >= 1 supervisor restart
    is observed; both pools pass the leak probe."""
    ref, replicas, proxy, router, front, _m, _p = fleet
    _reset_fleet(replicas, proxy, router)
    serve0, serve1 = replicas

    keys = jax.random.split(rng, 32)
    shared = np.asarray(jax.random.randint(keys[0], (32,), 0, 256))
    prompts, news = [], []
    for i in range(20):
        if i % 4 == 3:                    # bimodal: every 4th is a cold long
            p = np.asarray(jax.random.randint(keys[i + 1], (20,), 0, 256))
            n = 8
        else:                             # shared 2-page prefix + unique tail
            tail = np.asarray(jax.random.randint(keys[i + 1],
                                                 (3 + i % 5,), 0, 256))
            p = np.concatenate([shared, tail])
            n = 3 + i % 4
        prompts.append(p)
        news.append(n)
    want = [np.asarray(ref.generate(p[None], max_new_tokens=n,
                                    do_sample=False))[0, len(p):]
            for p, n in zip(prompts, news)]

    results = [None] * len(prompts)
    backpressure = {"429": 0, "503": 0}
    errors = []

    def client(i):
        """A well-behaved client: it honors backpressure — 429 waits out
        the Retry-After and retries, a router-level 503 (fleet busy
        failing over) backs off and retries — and treats 200/504/4xx as
        terminal.  Retrying cannot double-answer: 429/503 mean no answer
        was produced for this client (shed = never admitted; requeue =
        torn down undelivered)."""
        last = None
        for _attempt in range(8):
            try:
                last = _post(front.url,
                             {"prompt": prompts[i].tolist(),
                              "max_new_tokens": news[i],
                              "session": f"sess-{i % 3}",
                              "timeout": 90})
                break
            except urllib.error.HTTPError as exc:
                try:
                    body = json.load(exc)
                except Exception:
                    body = {}
                last = (exc.code, body)
                if exc.code == 429:
                    backpressure["429"] += 1
                    time.sleep(min(float(body.get("retry_after_s", 0.2)),
                                   0.5))
                    continue
                if exc.code == 503:
                    backpressure["503"] += 1
                    time.sleep(0.2)
                    continue
                break
            except Exception as exc:      # noqa: BLE001 - collected below
                errors.append((i, repr(exc)))
                return
        results[i] = last

    restarts = {"n": 0}
    watcher_stop = threading.Event()

    def supervisor_watcher():
        """The serve_supervisor restart loop, in process: a replica whose
        loop died and whose health flipped not-ready is revived (restart
        the loop — which processes the crash-teardown aborts — and
        resume admission) after a short backoff."""
        while not watcher_stop.is_set():
            for s in (serve0, serve1):
                if s._loop_crashed and not s._loop_alive():
                    time.sleep(0.2)       # the restart ladder's backoff
                    s.start_loop()
                    s.resume_admission()
                    restarts["n"] += 1
            time.sleep(0.02)

    router.start()
    watcher = threading.Thread(target=supervisor_watcher, daemon=True)
    watcher.start()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    try:
        # the kill is armed before traffic: replica 1's loop dies at its
        # 3rd step after this point — mid-trace, with requests on board.
        # Arrivals are staggered (a burst beyond fleet capacity just
        # sheds everything — the overload path has its own test)
        with crash_on_call(serve1, "step", n=3):
            for i, t in enumerate(threads):
                t.start()
                if i == 8:
                    # network chaos on replica 0 mid-trace: one
                    # delivered-then-reset (the ambiguous death after
                    # the work happened) and one refused connection
                    proxy.inject("deliver_then_reset")
                    proxy.inject("refuse")
                time.sleep(0.03)
            for t in threads:
                t.join(timeout=180)
            assert all(not t.is_alive() for t in threads), "client hung"
    finally:
        watcher_stop.set()
        watcher.join(timeout=10)

    assert not errors, errors
    assert all(r is not None for r in results)
    sheds, answered = [], 0
    for i, (code, body) in enumerate(results):
        assert code in (200, 429), (i, code, body)
        if code == 429:
            # cleanly shed even after the client's retries: explicit
            # backoff, no partial answer
            assert body.get("shed") is True and body.get("retry_after_s")
            sheds.append(i)
            continue
        answered += 1
        np.testing.assert_array_equal(
            np.asarray(body["tokens"]), want[i],
            err_msg=f"request {i} diverged (served by {body['replica']})")
    # exactly-once: every non-shed request has exactly one 200, token-
    # identical; nothing was dropped (200 + 429 partition the trace)
    assert answered + len(sheds) == len(prompts)
    assert answered >= (len(prompts) * 3) // 4, \
        f"too much shed to call this a served trace: {sheds}"
    # the kill fired and the supervisor-style restart was observed
    assert restarts["n"] >= 1, "no supervisor restart observed"
    # the fleet healed: both replicas serve again, leak-free
    _reset_fleet(replicas, proxy, router)
    _quiesce(serve0)
    _quiesce(serve1)
    code, body = _post(front.url, {"prompt": prompts[0].tolist(),
                                   "max_new_tokens": news[0]})
    assert code == 200
    np.testing.assert_array_equal(np.asarray(body["tokens"]), want[0])


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_drain_racing_concurrent_kill(fleet, rng):
    """Satellite: replica 0 is draining (loop stepping, drain waiting)
    when its loop is KILLED mid-drain.  drain() returns instead of
    hanging, the in-flight requests are handed back (503 requeue) and
    the router re-serves them on replica 1 token-identically — the e2e
    stays exactly-once."""
    ref, replicas, proxy, router, front, _m, _p = fleet
    _reset_fleet(replicas, proxy, router)
    serve0, serve1 = replicas
    prompts = [np.asarray(jax.random.randint(k, (10,), 0, 256))
               for k in jax.random.split(rng, 4)]
    want = [np.asarray(ref.generate(p[None], max_new_tokens=24,
                                    do_sample=False))[0, len(p):]
            for p in prompts]
    # aim the trace at replica 0 via session affinity (robust against
    # the background poll refreshing load views): the crash pops the
    # pin and the retry re-pins wherever it lands
    with router._lock:
        router._affinity["drain-race"] = ("repl0", time.monotonic())
    results = [None] * len(prompts)
    errors = []

    def client(i):
        try:
            results[i] = _post(front.url, {"prompt": prompts[i].tolist(),
                                           "max_new_tokens": 24,
                                           "session": "drain-race",
                                           "timeout": 90})
        except Exception as exc:          # noqa: BLE001
            errors.append((i, repr(exc)))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    with crash_on_call(serve0, "step", n=4):
        for t in threads:
            t.start()
        # wait until replica 0 actually has work on board
        deadline = time.monotonic() + 15
        while serve0.scheduler.num_occupied == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        drain_out = {}

        def drainer():
            drain_out["finished"] = serve0.drain(timeout=60)

        dt = threading.Thread(target=drainer)
        dt.start()                        # drain waits on the loop…
        dt.join(timeout=120)              # …which the injected fault kills
        assert not dt.is_alive(), "drain() hung through the kill"
        for t in threads:
            t.join(timeout=120)
        assert all(not t.is_alive() for t in threads)
    assert not errors, errors
    for i, (code, body) in enumerate(results):
        assert code == 200, (i, body)
        np.testing.assert_array_equal(
            np.asarray(body["tokens"]), want[i],
            err_msg=f"request {i} diverged through the drain+kill race")
    # the dead replica recovered via the supervisor action; its aborted
    # slots tear down on the revived loop and nothing leaks
    serve0.start_loop()
    serve0.resume_admission()
    _quiesce(serve0)
    _reset_fleet(replicas, proxy, router)


# ---------------------------------------------------------------------------
# disaggregated streaming chaos (ISSUE 19): a decode replica dies
# mid-stream, the router resumes from token N on a survivor.  Marked
# slow — rides `make chaos` (the tier-1 resume/identity coverage is
# tests/unit/test_disagg_serving.py)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_decode_replica_killed_mid_stream_resumes_on_survivor(devices):
    """Kill the decode replica serving a token stream mid-generation:
    the router's relay re-dispatches with ``resume_from=N`` onto the
    surviving decode replica and splices the suffix — the client reads
    ONE contiguous stream, token-identical to ``generate()``, with no
    token sent twice and exactly one regeneration (no double-answer)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    params = model.init(jax.random.PRNGKey(11), jnp.zeros((1, 8), jnp.int32))
    cfg = {"dtype": "float32", "max_out_tokens": 128, "kv_page_tokens": 16,
           "quantize_kv_cache": True, "max_queue_depth": 4}
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(12), (21,), 0, 256),
        dtype=np.int32)
    max_new = 64
    ref = deepspeed_tpu.init_inference(model, config=dict(cfg))
    ref.set_params(params)
    want = [int(t) for t in np.asarray(ref.generate(
        prompt[None], max_new_tokens=max_new,
        do_sample=False))[0, len(prompt):]]
    roles = ("prefill", "decode", "decode")
    replicas = []
    router = front = None
    try:
        for role in roles:
            s = deepspeed_tpu.init_serving(
                model, config=dict(cfg), num_slots=2, prefill_chunk=16,
                decode_block_tokens=2, role=role, metrics_port=0,
                registry=MetricsRegistry().enable(), private_health=True,
                serve_loop=True)
            s.set_params(params)
            replicas.append(s)
        router = Router(
            [f"{r}{i}@{r}={s.metrics_server.url}"
             for i, (r, s) in enumerate(zip(roles, replicas))],
            registry=MetricsRegistry().enable(), dispatch_rounds=6,
            retry_backoff=0.02, poll_interval=0.05, poll_timeout=1.0,
            request_timeout=120.0)
        router.refresh()
        front = RouterServer(router).start()
        decodes = replicas[1:]
        got, events = [], []
        first_chunk = threading.Event()
        stream_done = threading.Event()

        def client():
            req = urllib.request.Request(
                front.url + "/generate",
                data=json.dumps({"prompt": prompt.tolist(),
                                 "max_new_tokens": max_new,
                                 "stream": True, "timeout": 90}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    for line in resp:
                        ev = json.loads(line)
                        events.append(ev)
                        if ev.get("tokens"):
                            got.extend(ev["tokens"])
                            first_chunk.set()
                        if ev.get("done") or ev.get("error"):
                            break
            finally:
                first_chunk.set()
                stream_done.set()

        t = threading.Thread(target=client)
        t.start()
        assert first_chunk.wait(timeout=120), "stream never produced"
        # find the decode replica streaming this request and kill its
        # serving loop at the next step boundary (mid-generation)
        victim = next(s for s in decodes if s.scheduler.num_occupied)
        survivor = next(s for s in decodes if s is not victim)
        assert len(got) < max_new, "generation finished before the kill"
        with crash_on_call(victim, "step", n=1):
            t.join(timeout=120)
        assert stream_done.is_set()
        final = events[-1]
        assert final.get("done") is True, f"stream ended badly: {final}"
        # contiguous, token-identical, nothing sent twice
        assert got == want
        assert final["n"] == len(want)
        # cumulative n across token events is strictly increasing with
        # no overlap — the resumed suffix started exactly at N
        ns = [ev["n"] for ev in events if ev.get("tokens")]
        assert ns == sorted(set(ns))
        # the resume really crossed replicas: the survivor saw a
        # resume_from > 0 dispatch, the router logged the resume hop and
        # a retry, and exactly TWO generations ran fleet-wide (the
        # killed original + the survivor's regeneration — no fan-out)
        assert survivor._registry.get(
            "ds_serve_stream_resumes_total").value >= 1
        # the hop record files in the relay's finally on the front's
        # handler thread — give it a beat after the client hangs up
        deadline = time.monotonic() + 10
        while router.registry.get(
                "ds_router_hops_total",
                labels={"kind": "resume"}).value < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.registry.get(
            "ds_router_hops_total", labels={"kind": "resume"}).value >= 1
        assert router.registry.get("ds_router_retries_total").value >= 1
        subs = sum(s._registry.get("ds_serve_submitted_total").value
                   for s in decodes)
        assert subs == 2, subs
        # the victim died for real (loop crashed, replica not ready)
        assert victim._loop_crashed
    finally:
        if front is not None:
            front.stop()
        if router is not None:
            router.stop()
        for s in replicas:
            s.close()
