"""Block-sparse attention tests (reference: tests/unit/ops/sparse_attention).

Parity target: dense attention with the equivalent elementwise mask.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention,
                                                VariableSparsityConfig,
                                                block_sparse_attention)

NEG_INF = -1e30


def _dense_masked(q, k, v, layout, block, causal):
    H = q.shape[1]
    S = q.shape[2]
    mask = np.kron(layout, np.ones((block, block)))[:, :S, :S].astype(bool)
    if causal:
        mask &= np.tril(np.ones((S, S), bool))
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (q.shape[-1] ** 0.5)
    s = jnp.where(jnp.asarray(mask)[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("cfg_cls,causal", [
    (FixedSparsityConfig, False),
    (BigBirdSparsityConfig, False),
    (BSLongformerSparsityConfig, False),
    (VariableSparsityConfig, False),
    (FixedSparsityConfig, True),
])
def test_matches_masked_dense(rng, cfg_cls, causal):
    B, H, S, D = 2, 2, 64, 16
    block = 16
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))
    cfg = cfg_cls(num_heads=H, block=block,
                  attention="unidirectional" if causal else "bidirectional")
    layout = cfg.make_layout(S)
    assert layout.shape == (H, S // block, S // block)
    got = block_sparse_attention(q, k, v, layout, block, causal=causal)
    want = _dense_masked(q, k, v, layout, block, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_dense_config_equals_full_attention(rng):
    B, H, S, D = 1, 2, 32, 8
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))
    cfg = DenseSparsityConfig(num_heads=H, block=8)
    got = SparseSelfAttention(cfg)(q, k, v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (D ** 0.5)
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_layout_actually_sparse():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    layout = cfg.make_layout(256)  # 16x16 blocks
    density = layout.mean()
    assert density < 0.5, f"fixed layout should be sparse, got {density:.2f}"


def test_gradients_flow(rng):
    B, H, S, D = 1, 1, 32, 8
    q = jax.random.normal(rng, (B, H, S, D))
    cfg = FixedSparsityConfig(num_heads=H, block=8, num_local_blocks=2)
    layout = cfg.make_layout(S)

    def f(q):
        return block_sparse_attention(q, q, q, layout, 8).astype(jnp.float32).sum()

    g = jax.grad(f)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_masks_and_rpe_match_dense(rng):
    """VERDICT r4 item 7: rpe / key_padding_mask / attn_mask on a dense
    layout must reproduce plain softmax attention with the same score
    modifiers, in every mode combination."""
    from deepspeed_tpu.ops.sparse_attention import DenseSparsityConfig

    B, H, S, D = 2, 2, 32, 8
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))
    rpe = jax.random.normal(jax.random.fold_in(rng, 3), (H, S, S)) * 0.3
    kpm_add = jnp.where(jnp.arange(S) >= S - 4, -1e9, 0.0)[None, :].repeat(B, 0)
    kpm_mul = jnp.where(jnp.arange(S) >= S - 4, 0.0, 1.0)[None, :].repeat(B, 0)
    am_add = jnp.triu(jnp.full((S, S), -1e9), k=1)        # causal via mask
    am_mul = jnp.tril(jnp.ones((S, S)))

    def dense(q, k, v, rpe=None, kpm=None, am=None, kpm_mode="add",
              am_mode="mul"):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (D ** 0.5)
        if rpe is not None:
            s = s + rpe[None]
        if kpm is not None:
            s = (s + kpm[:, None, None, :] if kpm_mode == "add"
                 else s * kpm[:, None, None, :])
        if am is not None:
            s = s + am[None, None] if am_mode == "add" else s * am[None, None]
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

    cfg = DenseSparsityConfig(num_heads=H, block=8)
    for kwargs, dkw in (
        (dict(rpe=rpe), dict(rpe=rpe)),
        (dict(key_padding_mask=kpm_add), dict(kpm=kpm_add)),
        (dict(attn_mask=am_add), dict(am=am_add, am_mode="add")),
        (dict(rpe=rpe, key_padding_mask=kpm_add, attn_mask=am_add),
         dict(rpe=rpe, kpm=kpm_add, am=am_add, am_mode="add")),
    ):
        attn = SparseSelfAttention(
            cfg, attn_mask_mode="add" if "attn_mask" in kwargs else "mul")
        got = attn(q, k, v, **kwargs)
        want = dense(q, k, v, **dkw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5, err_msg=str(kwargs))
    # mul modes: the reference multiplies raw scores (NOT a masked softmax);
    # parity against the same literal semantics
    attn = SparseSelfAttention(cfg, key_padding_mask_mode="mul",
                               attn_mask_mode="mul")
    got = attn(q, k, v, key_padding_mask=kpm_mul, attn_mask=am_mul)
    want = dense(q, k, v, kpm=kpm_mul, am=am_mul, kpm_mode="mul",
                 am_mode="mul")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_key_padding_isolates_padded_keys(rng):
    """-inf key padding on a SPARSE layout: changing padded K/V content
    must not change any output row."""
    B, H, S, D = 1, 2, 64, 8
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))
    cfg = FixedSparsityConfig(num_heads=H, block=8, num_local_blocks=2,
                              num_global_blocks=1)
    kpm = jnp.where(jnp.arange(S) >= S - 8, -1e9, 0.0)[None, :]
    attn = SparseSelfAttention(cfg)
    out1 = attn(q, k, v, key_padding_mask=kpm)
    k2 = k.at[:, :, S - 8:, :].set(99.0)
    v2 = v.at[:, :, S - 8:, :].set(-99.0)
    out2 = attn(q, k2, v2, key_padding_mask=kpm)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-5, atol=1e-6)
