"""Block-sparse attention tests (reference: tests/unit/ops/sparse_attention).

Parity target: dense attention with the equivalent elementwise mask.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                DenseSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention,
                                                VariableSparsityConfig,
                                                block_sparse_attention)

NEG_INF = -1e30


def _dense_masked(q, k, v, layout, block, causal):
    H = q.shape[1]
    S = q.shape[2]
    mask = np.kron(layout, np.ones((block, block)))[:, :S, :S].astype(bool)
    if causal:
        mask &= np.tril(np.ones((S, S), bool))
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (q.shape[-1] ** 0.5)
    s = jnp.where(jnp.asarray(mask)[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("cfg_cls,causal", [
    (FixedSparsityConfig, False),
    (BigBirdSparsityConfig, False),
    (BSLongformerSparsityConfig, False),
    (VariableSparsityConfig, False),
    (FixedSparsityConfig, True),
])
def test_matches_masked_dense(rng, cfg_cls, causal):
    B, H, S, D = 2, 2, 64, 16
    block = 16
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))
    cfg = cfg_cls(num_heads=H, block=block,
                  attention="unidirectional" if causal else "bidirectional")
    layout = cfg.make_layout(S)
    assert layout.shape == (H, S // block, S // block)
    got = block_sparse_attention(q, k, v, layout, block, causal=causal)
    want = _dense_masked(q, k, v, layout, block, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_dense_config_equals_full_attention(rng):
    B, H, S, D = 1, 2, 32, 8
    q = jax.random.normal(rng, (B, H, S, D))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, H, S, D))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, H, S, D))
    cfg = DenseSparsityConfig(num_heads=H, block=8)
    got = SparseSelfAttention(cfg)(q, k, v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (D ** 0.5)
    want = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_layout_actually_sparse():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    layout = cfg.make_layout(256)  # 16x16 blocks
    density = layout.mean()
    assert density < 0.5, f"fixed layout should be sparse, got {density:.2f}"


def test_gradients_flow(rng):
    B, H, S, D = 1, 1, 32, 8
    q = jax.random.normal(rng, (B, H, S, D))
    cfg = FixedSparsityConfig(num_heads=H, block=8, num_local_blocks=2)
    layout = cfg.make_layout(S)

    def f(q):
        return block_sparse_attention(q, q, q, layout, 8).astype(jnp.float32).sum()

    g = jax.grad(f)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0
