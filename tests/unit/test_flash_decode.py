"""Length-aware flash-decode attention (VERDICT r3 weak #10): numerical
parity with the dense masked path, and the length bound (visited blocks
track the current position, not Smax)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.decoding import (DECODE_BLOCK,
                                           _cached_attention_dense,
                                           _cached_attention_flash_decode,
                                           _quantize_kv_rows)


def _setup(B=2, H=4, Hkv=2, Smax=4 * DECODE_BLOCK, Dh=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, 1, Dh), jnp.float32)
    k = jnp.asarray(rng.randn(B, Hkv, Smax, Dh), jnp.float32)
    v = jnp.asarray(rng.randn(B, Hkv, Smax, Dh), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("pos", [0, 5, DECODE_BLOCK - 1, DECODE_BLOCK,
                                 3 * DECODE_BLOCK + 17])
def test_flash_decode_matches_dense(pos):
    q, k, v = _setup()
    q_pos = jnp.asarray([pos], jnp.int32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    want = _cached_attention_dense(q, k, v, q_pos, scale)
    got = _cached_attention_flash_decode(q, k, v, q_pos, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_flash_decode_matches_dense_int8_kv():
    q, k, v = _setup(seed=3)
    kq, ks = _quantize_kv_rows(k)
    vq, vs = _quantize_kv_rows(v)
    q_pos = jnp.asarray([2 * DECODE_BLOCK + 3], jnp.int32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    want = _cached_attention_dense(q, kq, vq, q_pos, scale, ks, vs)
    got = _cached_attention_flash_decode(q, kq, vq, q_pos, scale, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_flash_decode_visits_only_needed_blocks():
    """The while_loop trip count is position-bound: corrupt the cache BEYOND
    the needed blocks with NaNs — dense would propagate them through masked
    lanes' exp; flash-decode must never read them."""
    q, k, v = _setup()
    Smax = k.shape[2]
    # poison everything from block 1 onward
    k = k.at[:, :, DECODE_BLOCK:].set(jnp.nan)
    v = v.at[:, :, DECODE_BLOCK:].set(jnp.nan)
    q_pos = jnp.asarray([7], jnp.int32)  # inside block 0
    scale = 1.0 / np.sqrt(q.shape[-1])
    out = _cached_attention_flash_decode(q, k, v, q_pos, scale)
    assert np.isfinite(np.asarray(out)).all()
