"""Inference tests (reference analog: tests/unit/inference/, SURVEY.md §4):
KV-cache decode parity vs full forward, generation, TP serving."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.models.decoding import forward_with_cache, init_kv_cache, sample_token


@pytest.fixture()
def tiny_model(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    return causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                     intermediate_size=128, num_heads=4, num_kv_heads=2,
                     vocab_size=256, remat=False)


def test_cached_forward_matches_full(tiny_model, rng):
    """Prefill-through-cache logits == training-path logits (fp32 cache)."""
    toks = jax.random.randint(rng, (2, 16), 0, 256)
    params = tiny_model.init(rng, toks)
    full = jax.jit(tiny_model.apply)(params, toks)
    cache = init_kv_cache(tiny_model.config, 2, 32, dtype=jnp.float32)
    logits, cache = jax.jit(
        lambda p, t, c: forward_with_cache(tiny_model, p, t, c, 0))(params, toks, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_prefill(tiny_model, rng):
    """Token-by-token decode reproduces the all-at-once prefill logits."""
    toks = jax.random.randint(rng, (1, 8), 0, 256)
    params = tiny_model.init(rng, toks)
    cache = init_kv_cache(tiny_model.config, 1, 16, dtype=jnp.float32)
    full_logits, _ = forward_with_cache(tiny_model, params, toks, cache, 0)

    cache = init_kv_cache(tiny_model.config, 1, 16, dtype=jnp.float32)
    step = jax.jit(lambda p, t, c, s: forward_with_cache(tiny_model, p, t, c, s))
    outs = []
    for i in range(8):
        logits, cache = step(params, toks[:, i:i + 1], cache, i)
        outs.append(logits[:, 0])
    inc = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full_logits),
                               rtol=2e-4, atol=2e-4)


def test_init_inference_generate(tiny_model, rng):
    toks = jax.random.randint(rng, (2, 8), 0, 256)
    params = tiny_model.init(rng, toks)
    engine = deepspeed_tpu.init_inference(
        tiny_model, config={"dtype": "float32", "max_out_tokens": 64})
    engine.set_params(params)
    out = engine.generate(toks, max_new_tokens=8)
    assert out.shape == (2, 16)
    assert np.array_equal(np.asarray(out[:, :8]), np.asarray(toks))
    # greedy determinism
    out2 = engine.generate(toks, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_generate_eos_early_stop(tiny_model, rng):
    toks = jax.random.randint(rng, (1, 4), 0, 256)
    params = tiny_model.init(rng, toks)
    engine = deepspeed_tpu.init_inference(
        tiny_model, config={"dtype": "float32", "max_out_tokens": 64})
    engine.set_params(params)
    # pick the model's actual greedy first token as "eos" to force early stop
    first = int(engine.generate(toks, max_new_tokens=1)[0, -1])
    out = engine.generate(toks, max_new_tokens=8, eos_token_id=first)
    assert (np.asarray(out[0, 4:]) == first).all()


def test_sample_token_top_k():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    rng = jax.random.PRNGKey(0)
    for _ in range(20):
        rng, k = jax.random.split(rng)
        tok = sample_token(logits, k, top_k=2, do_sample=True)
        assert int(tok[0]) in (0, 1)
    tok = sample_token(logits, rng, do_sample=False)
    assert int(tok[0]) == 0


def test_tp_inference(devices, rng):
    """Serving with tp=2: same logits as unsharded."""
    mesh = build_mesh(fsdp=4, tp=2, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    toks = jax.random.randint(rng, (2, 8), 0, 256)
    params = model.init(rng, toks)
    # init_inference signature parity: config kwargs path
    engine = deepspeed_tpu.init_inference(
        model, dtype="float32", tensor_parallel={"tp_size": 2}, max_out_tokens=32)
    engine.set_params(params)
    out = engine.generate(toks, max_new_tokens=4)
    assert out.shape == (2, 12)


def test_generate_bucketed_prefill_matches_exact(tiny_model, rng):
    """A prompt whose length is not a bucket size (5 -> bucket 16) must
    produce the same greedy continuation as manual exact-length decode."""
    toks = jax.random.randint(rng, (2, 5), 0, 256)
    params = tiny_model.init(rng, toks)
    engine = deepspeed_tpu.init_inference(
        tiny_model, config={"dtype": "float32", "max_out_tokens": 64})
    engine.set_params(params)
    out = engine.generate(toks, max_new_tokens=6, do_sample=False)

    # manual: exact-length prefill + greedy decode
    cache = init_kv_cache(tiny_model.config, 2, 64, dtype=jnp.float32)
    logits, cache = forward_with_cache(tiny_model, engine._params, toks, cache, 0)
    cur = jnp.argmax(logits[:, -1], axis=-1)
    want = [cur]
    pos = 5
    for _ in range(5):
        logits, cache = forward_with_cache(tiny_model, engine._params,
                                           cur[:, None], cache, pos)
        cur = jnp.argmax(logits[:, -1], axis=-1)
        want.append(cur)
        pos += 1
    np.testing.assert_array_equal(np.asarray(out[:, 5:]),
                                  np.asarray(jnp.stack(want, axis=1)))


def test_batch_bucket_reuse_and_reentrancy_guard(tiny_model, rng):
    """(a) A batch-3 call after a batch-8 call must REUSE the batch-8
    cache allocation and compiled programs (batch pads up to the bucket)
    instead of reallocating + recompiling — and produce the same rows (row
    independence: padding rows cannot perturb real rows).  (b) generate()
    donates + nulls the cache mid-call; re-entry must raise a clear
    RuntimeError instead of crashing inside XLA."""
    toks = jax.random.randint(rng, (8, 8), 0, 256)
    params = tiny_model.init(rng, toks)
    engine = deepspeed_tpu.init_inference(
        tiny_model, config={"dtype": "float32", "max_out_tokens": 64})
    engine.set_params(params)
    out8 = np.asarray(engine.generate(toks, max_new_tokens=4))
    assert engine._cache["k"].shape[1] == 8
    fns = engine._gen_fns
    prefills = engine._prefill_fns
    out3 = np.asarray(engine.generate(toks[:3], max_new_tokens=4))
    assert engine._cache["k"].shape[1] == 8, "batch-3 reallocated the cache"
    assert engine._gen_fns is fns and engine._prefill_fns is prefills, \
        "batch-3 dropped the batch-8 compiled fns"
    assert out3.shape == (3, 12)
    np.testing.assert_array_equal(out3, out8[:3])

    # (b) simulate re-entry from inside the running call (e.g. another
    # thread) by hooking the point where the cache has been donated
    real = engine._gen_loop

    def reenter(settings):
        with pytest.raises(RuntimeError, match="not reentrant"):
            engine.generate(toks, max_new_tokens=4)
        return real(settings)

    engine._gen_loop = reenter
    out = engine.generate(toks, max_new_tokens=4)
    assert out.shape == (8, 12)
    engine._gen_loop = real
    # and the flag must reset even after an inner failure
    assert engine.generate(toks, max_new_tokens=4).shape == (8, 12)


def test_generate_single_dispatch(tiny_model, rng, monkeypatch):
    """The whole decode loop must be ONE compiled call — count dispatches."""
    toks = jax.random.randint(rng, (1, 8), 0, 256)
    params = tiny_model.init(rng, toks)
    engine = deepspeed_tpu.init_inference(
        tiny_model, config={"dtype": "float32", "max_out_tokens": 64})
    engine.set_params(params)
    engine.generate(toks, max_new_tokens=4)  # warm the compile caches

    calls = {"n": 0}
    settings_key = next(iter(engine._gen_fns))
    real = engine._gen_fns[settings_key]

    def counted(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    engine._gen_fns[settings_key] = counted
    engine.generate(toks, max_new_tokens=4)  # same settings -> same program
    assert calls["n"] == 1, "decode loop should be a single jitted call"
