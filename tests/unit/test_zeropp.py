"""ZeRO++ tests (VERDICT r3 item 3 done-criteria): convergence parity vs
dense ZeRO-3 on the 8-device mesh + CommsLogger volume assertions showing
the quantized-collective reduction; hpZ secondary-partition training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm import comm as comm_api
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from tests.unit.simple_model import SimpleModel, random_dataset


def _train(zero_extra, steps=10, lr=1e-2, log_comms=False, gas=1):
    mesh = build_mesh(fsdp=8, devices=jax.devices())
    set_global_mesh(mesh)
    x, y = random_dataset(n=64, dim=16, out_dim=4)
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "Adam", "params": {"lr": lr}},
           "gradient_clipping": 1.0,
           "comms_logger": {"enabled": log_comms},
           "zero_optimization": {"stage": 3, **zero_extra}}
    if log_comms:
        comm_api.comms_logger.reset()
        comm_api.comms_logger.enabled = True
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg, mesh=mesh,
        rng=jax.random.PRNGKey(7))
    losses = []
    bsz = 16 * gas
    for i in range(steps):
        lo = (i * bsz) % (64 - bsz + 1)
        losses.append(float(engine.train_step((x[lo:lo + bsz],
                                               y[lo:lo + bsz]))))
    return losses, engine


def test_zeropp_activates_and_trains():
    losses, engine = _train({"zero_quantized_weights": True,
                             "zero_quantized_gradients": True})
    assert engine._zeropp_active()
    assert engine._inert_config_keys == []
    assert losses[-1] < losses[0] * 0.7, losses


def test_zeropp_convergence_parity_vs_dense_zero3():
    dense, dense_engine = _train({}, steps=12)
    qboth, engine = _train({"zero_quantized_weights": True,
                            "zero_quantized_gradients": True}, steps=12)
    assert engine._zeropp_active()
    # int8 blocks add bounded noise; trajectories must stay close
    np.testing.assert_allclose(qboth, dense, rtol=0.15)
    assert qboth[-1] < qboth[0] * 0.6
    # grad SCALE parity: Adam hides a uniformly mis-scaled gradient (its
    # update normalizes by sqrt(v)), so assert the reported global grad
    # norm matches the GSPMD engine's — catches sum-vs-mean bugs over the
    # fsdp axis that convergence alone cannot.
    gn_q = float(engine._last_grad_norm)
    gn_d = float(dense_engine._last_grad_norm)
    assert abs(gn_q - gn_d) < 0.2 * max(gn_d, 1e-6), (gn_q, gn_d)


def test_zeropp_comm_volume_reduction():
    """The point of ZeRO++: the wire carries int8 payloads.  Per-element
    gather/RS bytes must come in well under the dense fp32 path (~4x; the
    scales add ~block overhead)."""
    _, dense_engine = _train({"zero_hpz_partition_size": 1}, steps=2,
                             log_comms=True)
    # dense ZeRO-3 here runs under GSPMD (no explicit records), so measure
    # the zeropp dense fallback instead: hpz=2 without quantization uses
    # dense (bf16/fp32) collectives through the same recorded path.
    dense_counts = dict(comm_api.comms_logger.bytes)

    _, q_engine = _train({"zero_quantized_weights": True,
                          "zero_quantized_gradients": True}, steps=2,
                         log_comms=True)
    q_counts = dict(comm_api.comms_logger.bytes)
    comm_api.comms_logger.enabled = False

    q_ag = sum(v for k, v in q_counts.items() if "q_all_gather" in k)
    q_rs = sum(v for k, v in q_counts.items() if "q_reduce_scatter" in k)
    assert q_ag > 0 and q_rs > 0, q_counts
    d_ag = sum(v for k, v in dense_counts.items() if "zpp_all_gather" in k)
    d_rs = sum(v for k, v in dense_counts.items() if "zpp_reduce_scatter" in k)
    if d_ag and d_rs:
        # same number of collective calls per step; quantized payloads are
        # int8 (1B) vs fp32 (4B) -> ~4x smaller (scales overhead < 2%)
        assert q_ag < 0.35 * d_ag, (q_ag, d_ag)
        assert q_rs < 0.35 * d_rs, (q_rs, d_rs)


def test_zeropp_inactive_falls_back_with_warning():
    # stage 1 cannot take the ZeRO++ path: engine falls back to the GSPMD
    # path and warns (covered in test_config_honesty as well)
    mesh = build_mesh(fsdp=8, devices=jax.devices())
    set_global_mesh(mesh)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1,
                                      "zero_quantized_gradients": True}},
        mesh=mesh)
    assert not engine._zeropp_active()
    assert "zero_quantized_gradients" in " ".join(engine._inert_config_keys)


class TestHpZ:
    def test_hpz_trains_and_uses_subgroup_gathers(self):
        comm_api.comms_logger.reset()
        losses, engine = _train({"zero_quantized_weights": True,
                                 "zero_quantized_gradients": True,
                                 "zero_hpz_partition_size": 2}, steps=10,
                                log_comms=True)
        comm_api.comms_logger.enabled = False
        assert engine._zeropp_active()
        assert engine._zpp_cfg.hpz == 2
        assert losses[-1] < losses[0] * 0.7, losses
        keys = " ".join(comm_api.comms_logger.counts)
        assert "zpp_q_all_gather(hpz)" in keys, keys

    def test_hpz_dense_secondary_parity(self):
        # hpz with quantization OFF: bf16 secondary, must track plain dense
        dense, _ = _train({}, steps=10)
        hpz, engine = _train({"zero_hpz_partition_size": 4}, steps=10)
        assert engine._zeropp_active()
        np.testing.assert_allclose(hpz, dense, rtol=0.1)

    def test_hpz_invalid_size_warns_inert(self):
        losses, engine = _train({"zero_hpz_partition_size": 3}, steps=2)
        assert not engine._zeropp_active()  # 3 does not divide fsdp=8
        assert "hpz" in (engine._zeropp_reason or "")


def test_zeropp_checkpoint_roundtrip(tmp_path):
    losses, engine = _train({"zero_quantized_weights": True,
                             "zero_quantized_gradients": True,
                             "zero_hpz_partition_size": 2}, steps=4)
    before = jax.device_get(engine.state.params.primary)
    engine.save_checkpoint(str(tmp_path))
    _train_more = [float(engine.train_step((
        jnp.ones((16, 16), jnp.float32), jnp.ones((16, 4), jnp.float32))))
        for _ in range(2)]
    engine.load_checkpoint(str(tmp_path))
    after = jax.device_get(engine.state.params.primary)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zeropp_save_16bit_model_exports_full_shapes(tmp_path):
    losses, engine = _train({"zero_quantized_weights": True}, steps=2)
    out = engine.save_16bit_model(str(tmp_path))
    from deepspeed_tpu.runtime.checkpoint_engine import is_sharded_checkpoint

    assert is_sharded_checkpoint(out)
