"""Metrics registry (monitor/metrics.py): histogram bucket/quantile
correctness, snapshot consistency under concurrent writes, the Prometheus
exposition golden format, the disabled-path cost contract (one branch, no
allocation), the MonitorMaster bridge, the bench BENCH_JSON handshake, and
the tier-1 NAMESPACE GUARD — every metric the suite registers must live in
the ``ds_`` namespace and be documented in docs/OBSERVABILITY.md."""

import json
import os
import re
import sys
import threading

import pytest

from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry

# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------


def test_counter_gauge_basic():
    reg = MetricsRegistry().enable()
    c = reg.counter("ds_t_reqs_total")
    g = reg.gauge("ds_t_depth")
    c.inc()
    c.inc(4)
    g.set(3)
    g.set(7.5)
    assert c.value == 5
    assert g.value == 7.5
    # create-or-return: same (name, labels) is the same instrument
    assert reg.counter("ds_t_reqs_total") is c
    reg.reset()
    assert c.value == 0 and g.value == 0.0


def test_histogram_bucket_assignment():
    reg = MetricsRegistry().enable()
    h = reg.histogram("ds_t_lat_seconds", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 3.0, 5.0):   # le semantics: 1.0 -> first bucket
        h.record(v)
    assert h._counts == [2, 1, 1, 1]
    assert h.count == 5
    assert h.sum == pytest.approx(11.0)


def test_histogram_quantiles_land_in_the_right_bucket():
    reg = MetricsRegistry().enable()
    h = reg.histogram("ds_t_lat_seconds")   # default log buckets 1us..100s
    for _ in range(100):
        h.record(0.01)
    for _ in range(100):
        h.record(1.0)
    # p50 must fall inside the bucket containing 0.01, p90 inside the one
    # containing 1.0 (log buckets at 4/decade: bucket width <= ~78%)
    assert 0.005 <= h.quantile(0.5) <= 0.02
    assert 0.5 <= h.quantile(0.9) <= 1.0 + 1e-9
    assert h.mean == pytest.approx(0.505)
    s = h.snapshot()
    assert s["count"] == 200 and s["p99"] <= 1.0 + 1e-9
    # all mass past the last bound: the overflow bucket reports the bound
    h2 = reg.histogram("ds_t_over_seconds", buckets=(1.0, 2.0))
    h2.record(100.0)
    assert h2.quantile(0.5) == 2.0


def test_histogram_snapshot_consistent_under_writes():
    """Reader thread sees count == sum(buckets) on EVERY snapshot while a
    writer hammers record() — the lock-free single-writer contract."""
    reg = MetricsRegistry().enable()
    h = reg.histogram("ds_t_lat_seconds")
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            h.record(0.37)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        last = 0
        for _ in range(300):
            s = h.snapshot()
            assert s["count"] == sum(s["buckets"])
            assert s["count"] >= last      # monotone under a single writer
            last = s["count"]
    finally:
        stop.set()
        t.join(timeout=5)
    assert h.count > 0


def test_disabled_path_records_nothing_and_allocates_nothing():
    reg = MetricsRegistry()                 # disabled by default
    c = reg.counter("ds_t_total")
    h = reg.histogram("ds_t_lat_seconds")
    v = 0.125
    c.inc()
    h.record(v)                             # warm any lazy machinery
    vals = [v] * 5000
    before = sys.getallocatedblocks()
    for x in vals:
        h.record(x)
        c.inc()
    delta = sys.getallocatedblocks() - before
    assert c.value == 0 and h.count == 0
    # one branch, no allocation per record: the block count may wiggle a
    # few blocks from interpreter internals, never per-call
    assert delta < 100


def test_duplicate_name_different_kind_raises():
    reg = MetricsRegistry()
    reg.counter("ds_t_thing")
    with pytest.raises(ValueError, match="already registered"):
        reg.histogram("ds_t_thing")
    # a name is uniformly labeled or uniformly bare: mixing would make the
    # snapshot shape ambiguous (crash/drop at scrape time otherwise)
    with pytest.raises(ValueError, match="without labels"):
        reg.counter("ds_t_thing", labels={"reason": "eos"})
    reg.counter("ds_t_fam", labels={"reason": "eos"})
    reg.counter("ds_t_fam", labels={"reason": "length"})  # fine: one kind
    with pytest.raises(ValueError, match="with labels"):
        reg.counter("ds_t_fam")
    # ...and the name still cannot cross kinds through a labeled variant
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("ds_t_fam", labels={"reason": "x"})


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

GOLDEN = """\
# TYPE ds_t_depth gauge
ds_t_depth 2
# HELP ds_t_finished_total by reason
# TYPE ds_t_finished_total counter
ds_t_finished_total{reason="eos"} 2
ds_t_finished_total{reason="length"} 1
# HELP ds_t_lat_seconds latency
# TYPE ds_t_lat_seconds histogram
ds_t_lat_seconds_bucket{le="0.1"} 1
ds_t_lat_seconds_bucket{le="1"} 2
ds_t_lat_seconds_bucket{le="10"} 3
ds_t_lat_seconds_bucket{le="+Inf"} 4
ds_t_lat_seconds_sum 55.55
ds_t_lat_seconds_count 4
# HELP ds_t_reqs_total help text
# TYPE ds_t_reqs_total counter
ds_t_reqs_total 3
"""


def test_prometheus_exposition_golden():
    reg = MetricsRegistry().enable()
    reg.counter("ds_t_reqs_total", "help text").inc(3)
    reg.gauge("ds_t_depth").set(2)
    h = reg.histogram("ds_t_lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.record(v)
    reg.counter("ds_t_finished_total", "by reason",
                labels={"reason": "eos"}).inc(2)
    reg.counter("ds_t_finished_total", labels={"reason": "length"}).inc()
    assert reg.prometheus_text() == GOLDEN


def test_statz_json_roundtrip():
    reg = MetricsRegistry().enable()
    reg.counter("ds_t_reqs_total").inc(2)
    reg.histogram("ds_t_lat_seconds", buckets=(1.0,)).record(0.5)
    reg.counter("ds_t_finished_total", labels={"reason": "eos"}).inc()
    snap = json.loads(reg.statz_json())
    assert snap["enabled"] is True
    m = snap["metrics"]
    assert m["ds_t_reqs_total"] == 2
    assert m["ds_t_lat_seconds"]["count"] == 1
    assert m["ds_t_finished_total"]['{reason="eos"}'] == 1


def test_monitor_master_bridge():
    """registry.publish fans counters/gauges/histogram summaries out as
    MonitorMaster events (CSV/TensorBoard backends see the same schema)."""
    reg = MetricsRegistry().enable()
    reg.counter("ds_t_reqs_total").inc(4)
    reg.gauge("ds_t_depth").set(3)
    h = reg.histogram("ds_t_lat_seconds", buckets=(1.0, 2.0))
    h.record(0.5)
    h.record(1.5)

    class FakeMonitor:
        enabled = True

        def __init__(self):
            self.events = []

        def write_events(self, events):
            self.events.extend(events)

    mon = FakeMonitor()
    reg.publish(mon, step=7)
    ev = {name: (value, step) for name, value, step in mon.events}
    assert ev["ds_t_reqs_total"] == (4, 7)
    assert ev["ds_t_depth"] == (3, 7)
    assert ev["ds_t_lat_seconds/count"][0] == 2
    assert ev["ds_t_lat_seconds/mean"][0] == pytest.approx(1.0)
    # disabled monitor: no events
    mon2 = FakeMonitor()
    mon2.enabled = False
    reg.publish(mon2, step=8)
    assert mon2.events == []


# ---------------------------------------------------------------------------
# /statz?window= rate deltas (two scrapes -> rates without Prometheus)
# ---------------------------------------------------------------------------


def test_statz_window_two_scrapes():
    """First scrape of a window key primes it; the second returns
    counter/histogram deltas + per-second rates over the real elapsed
    time.  Distinct keys keep independent baselines."""
    import time
    import urllib.request

    from deepspeed_tpu.monitor.server import MetricsServer

    reg = MetricsRegistry().enable()
    c = reg.counter("ds_t_reqs_total")
    h = reg.histogram("ds_t_lat_seconds", buckets=(1.0, 2.0))
    g = reg.gauge("ds_t_depth")
    c.inc(5)
    h.record(0.5)
    server = MetricsServer(reg, port=0).start()
    try:
        def scrape(q):
            with urllib.request.urlopen(f"{server.url}/statz?{q}",
                                        timeout=5) as r:
                return json.load(r)

        first = scrape("window=5")
        assert first["primed"] is True and first["metrics"] == {}
        c.inc(7)
        h.record(1.5)
        h.record(1.5)
        g.set(3)
        time.sleep(0.05)
        second = scrape("window=5")
        assert second["primed"] is False
        assert second["window_s"] > 0
        m = second["metrics"]
        assert m["ds_t_reqs_total"]["delta"] == 7
        assert m["ds_t_reqs_total"]["per_sec"] == pytest.approx(
            7 / second["window_s"], rel=0.2)
        assert m["ds_t_lat_seconds"]["count_delta"] == 2
        assert m["ds_t_lat_seconds"]["window_mean"] == pytest.approx(1.5)
        assert m["ds_t_depth"]["value"] == 3
        # a different key has its own baseline: full values as the delta
        other = scrape("window=60")
        assert other["primed"] is True
        c.inc(1)
        assert scrape("window=60")["metrics"]["ds_t_reqs_total"]["delta"] == 1
        # plain /statz is unchanged by windowed scrapes
        with urllib.request.urlopen(f"{server.url}/statz", timeout=5) as r:
            assert json.load(r)["metrics"]["ds_t_reqs_total"] == 13
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# bench handshake (satellite: BENCH_r05 "parsed": null)
# ---------------------------------------------------------------------------


def test_bench_summary_last_line_roundtrips_json():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    try:
        import bench
    finally:
        sys.path.pop(0)
    record = {"metric": "m", "value": 1.5, "unit": "tok/s",
              "vs_baseline": 0.5,
              "detail": {"mfu": 0.4, "backend": "cpu",
                         # the prefix-caching acceptance rung rides the
                         # record detail and surfaces in the summary
                         "prefix_serving_125m": {
                             "prefill_savings_ratio": 0.64,
                             "prefix_hit_ratio": 0.64,
                             "outputs_token_identical": True,
                             "prefix_goodput_speedup": 1.04,
                             "cache_on": {"ttft_p99_s": 0.017},
                             "cache_off": {"ttft_p99_s": 0.019}}}}
    serving = {"goodput_speedup": 2.0,
               "continuous": {"goodput_tok_s": 100.0, "p99_latency_s": 0.5},
               "metrics": {"ttft_p50_s": 0.01, "ttft_p99_s": 0.05,
                           "queue_wait_p99_s": 0.2,
                           "mean_slot_occupancy": 0.9,
                           "tail_attribution": {
                               "p": 0.99, "n": 64, "tail_n": 2,
                               "cut_s": 1.2, "dominant_phase": "queue",
                               "phase_share": {"queue": 0.8},
                               "exemplars": [7, 3]}}}
    lines = bench.summary_lines(record, serving)
    # the runner parses the LAST stdout line: it must be the bare object
    parsed = json.loads(lines[-1])
    assert parsed["metric"] == "m"
    assert parsed["serving_metrics"]["queue_wait_p99_s"] == 0.2
    # the ISSUE 7 tail-attribution sub-object rides BENCH_JSON verbatim
    ta = parsed["serving_metrics"]["tail_attribution"]
    assert ta["dominant_phase"] == "queue" and ta["exemplars"] == [7, 3]
    # the prefix-caching acceptance pair rides BENCH_JSON (round-trip
    # pinned: savings ratio + token-identity + hit ratio)
    pf = parsed["serving_prefix"]
    assert pf["prefill_savings_ratio"] == 0.64
    assert pf["outputs_token_identical"] is True
    assert pf["prefix_hit_ratio"] == 0.64
    assert pf["ttft_p99_on_s"] == 0.017 and pf["ttft_p99_off_s"] == 0.019
    # the human-greppable prefixed line stays, directly above it
    assert lines[-2] == "BENCH_JSON: " + lines[-1]
    # no serving rung (CPU smoke): still a parseable bare last line
    bare = {"metric": "m", "value": 1.5, "unit": "tok/s",
            "vs_baseline": 0.5, "detail": {"mfu": 0.4, "backend": "cpu"}}
    parsed = json.loads(bench.summary_lines(bare, None)[-1])
    assert "serving_metrics" not in parsed and "serving_prefix" not in parsed


def test_bench_summary_new_rungs_roundtrip_and_strip_bulk():
    """ISSUE 11 blocks ride BENCH_JSON (streamed_offload relay +
    serving_host_tier acceptance pair), and per-capture device_profile
    payloads are STRIPPED from the capped final line (they stay in the
    record line)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    try:
        import bench
    finally:
        sys.path.pop(0)
    record = {"metric": "m", "value": 1.5, "unit": "tok/s",
              "vs_baseline": 0.5,
              "detail": {
                  "mfu": 0.4, "backend": "cpu",
                  "metrics": {"tflops": 1.0,
                              "device_profile": {"huge": "x" * 500}},
                  "goodput": {
                      "wall_s": 1.0, "loop_s": 0.9, "goodput_ratio": 0.91,
                      "telescopes": True,
                      "categories": {"compute": 0.91, "recompile": 0.02,
                                     "idle": 0.07},
                      "tokens": 1536, "tokens_expected": 1536,
                      "tokens_reconcile": True, "tokens_per_sec": 1536.0},
                  "overlap_1b4": {
                      "overlap_speedup": 1.2, "loss_parity": True,
                      "off": {"tokens_per_sec": 100.0, "mfu": 0.5,
                              "comm_s": 0.004, "comm_s_source": "analytic",
                              "loss": 1.0},
                      "on": {"tokens_per_sec": 120.0, "mfu": 0.6,
                             "comm_s": 0.003, "comm_s_source": "device",
                             "loss": 1.0}},
                  "streamed_offload": {
                      "status": "ok", "streamed_speedup": 1.6,
                      "relay_bytes_ratio": 1.9, "loss_parity": True,
                      "gap_share": 0.31,
                      "bf16": {"relay_MBps": 14.0,
                               "device_profile": {"huge": "y" * 500}},
                      "int8": {"relay_MBps": 27.0}},
                  "host_tier_serving": {
                      "hit_ratio_on": 0.61, "hit_ratio_off": 0.42,
                      "outputs_token_identical": True, "demotes": 6,
                      "promotes": 5, "goodput_speedup": 1.1},
                  "fleet_chaos": {
                      "goodput_retention": 0.83,
                      "clean": {"goodput_tok_s": 120.0, "shed_429": 0},
                      "chaos": {"goodput_tok_s": 99.6, "shed_429": 2},
                      "ttft_p99_clean_s": 0.05, "ttft_p99_chaos_s": 0.4,
                      "restarts_observed": 1,
                      "answered_exactly_once": True,
                      "outputs_token_identical": True},
                  "disagg_serving": {
                      "handoff_compression": 1.94,
                      "handoff_wire_bytes": 54272,
                      "handoff_dense_bytes": 105472,
                      "disagg_goodput_ratio": 1.07,
                      "ttft_stream_over_total": 0.31,
                      "outputs_token_identical": True,
                      "mono": {"plain": {"goodput_tok_s": 90.0},
                               "stream": {"goodput_tok_s": 91.0}},
                      "disagg": {
                          "plain": {"goodput_tok_s": 95.0,
                                    "ttft_p50_s": 0.021,
                                    "device_profile": {"huge": "z" * 500}},
                          "stream": {"goodput_tok_s": 96.0,
                                     "ttft_p50_s": 0.012,
                                     "client_p50_s": 0.04}}},
                  "elastic_resume": {
                      "status": "ok", "world_save": 4, "worlds": [2, 8],
                      "resume_latency_s_max": 0.68,
                      "steps_to_recover_max": 0, "loss_parity": True,
                      "resumes": {"2": {"resume_latency_s": 0.68}}},
                  "quant_comm": {
                      "status": "ok",
                      "compression": {"q_all_reduce": 3.44,
                                      "q_all_gather": 3.94,
                                      "q_reduce_scatter": 3.94},
                      "loss_parity": {"all_reduce": True,
                                      "gather_rs": True},
                      "families": {
                          "all_reduce": {"speedup": 0.82,
                                         "dense": {"loss": 6.13},
                                         "int8": {"loss": 6.13}},
                          "gather_rs": {"speedup": 0.9,
                                        "dense": {"loss": 6.13},
                                        "int8": {"loss": 6.13}}}},
                  "pipe": {
                      "status": "ok",
                      "compression": {"pp2": 3.94, "pp4": 3.94},
                      "loss_parity": {"pp2": True, "pp4": True},
                      "bubble_share": {"pp2": 0.1667, "pp4": 0.3},
                      "rungs": {
                          "pp2": {"speedup": 1.0,
                                  "dense": {"loss": 6.14,
                                            "boundary_bytes": 6291456},
                                  "int8": {"loss": 6.14,
                                           "boundary_bytes": 1597440}},
                          "pp4": {"speedup": 1.15,
                                  "dense": {"loss": 6.12},
                                  "int8": {"loss": 6.12}}}}}}
    lines = bench.summary_lines(record, None)
    parsed = json.loads(lines[-1])
    # the ISSUE 18 goodput row rides BENCH_JSON: ratio + categories +
    # the telescoping / exact-token-reconciliation bits
    gpb = parsed["goodput"]
    assert gpb["goodput_ratio"] == 0.91 and gpb["telescopes"] is True
    assert gpb["tokens_reconcile"] is True
    assert gpb["tokens_per_sec"] == 1536.0
    assert gpb["categories"]["compute"] == 0.91
    # the overlap ablation's comm_s carries its source label (bench
    # honesty: analytic comm-plan pricing on CPU, device truth otherwise)
    ova = parsed["overlap_ablation"]
    assert ova["off"]["comm_s"] == 0.004
    assert ova["off"]["comm_s_source"] == "analytic"
    assert ova["on"]["comm_s_source"] == "device"
    st = parsed["streamed_offload"]
    assert st["streamed_speedup"] == 1.6
    assert st["relay_bytes_ratio"] == 1.9 and st["loss_parity"] is True
    assert st["gap_share"] == 0.31
    assert st["relay_MBps"] == {"bf16": 14.0, "int8": 27.0}
    ht = parsed["serving_host_tier"]
    assert ht["hit_ratio_on"] == 0.61 and ht["hit_ratio_off"] == 0.42
    assert ht["outputs_token_identical"] is True
    assert ht["demotes"] == 6 and ht["promotes"] == 5
    # the ISSUE 13 fleet-chaos acceptance row rides BENCH_JSON
    fc = parsed["fleet_chaos"]
    assert fc["goodput_retention"] == 0.83
    assert fc["goodput_clean_tok_s"] == 120.0
    assert fc["goodput_chaos_tok_s"] == 99.6
    assert fc["restarts_observed"] == 1 and fc["shed_429"] == 2
    assert fc["answered_exactly_once"] is True
    assert fc["outputs_token_identical"] is True
    # the ISSUE 19 disaggregated-serving acceptance row rides BENCH_JSON:
    # role-split goodput ratio, user-visible streaming TTFT, int8 KV
    # handoff compression vs the dense twin, grid-wide token identity
    dg = parsed["disagg_serving"]
    assert dg["disagg_goodput_ratio"] == 1.07
    assert dg["ttft_stream_p50_s"] == 0.012
    assert dg["ttft_stream_over_total"] == 0.31
    assert dg["handoff_compression"] == 1.94
    assert dg["outputs_token_identical"] is True
    # the ISSUE 14 elastic-resume acceptance row rides BENCH_JSON
    er = parsed["elastic_resume"]
    assert er["resume_latency_s"] == 0.68
    assert er["steps_to_recover"] == 0 and er["loss_parity"] is True
    assert er["world_save"] == 4 and er["worlds"] == [2, 8]
    # the ISSUE 15 quantized-collective ablation row rides BENCH_JSON
    qc = parsed["quant_comm"]
    assert qc["compression"]["q_all_reduce"] == 3.44
    assert qc["compression"]["q_all_gather"] == 3.94
    assert qc["loss_parity"] == {"all_reduce": True, "gather_rs": True}
    assert qc["speedup"] == {"all_reduce": 0.82, "gather_rs": 0.9}
    # the ISSUE 16 pipeline boundary ablation row rides BENCH_JSON
    pi = parsed["pipe"]
    assert pi["compression"] == {"pp2": 3.94, "pp4": 3.94}
    assert pi["loss_parity"] == {"pp2": True, "pp4": True}
    assert pi["bubble_share"] == {"pp2": 0.1667, "pp4": 0.3}
    assert pi["speedup"] == {"pp2": 1.0, "pp4": 1.15}
    # bulky capture payloads never reach the final line
    assert "device_profile" not in json.dumps(parsed)
    assert lines[-2] == "BENCH_JSON: " + lines[-1]


def test_bench_summary_line_capped():
    """An oversized summary drops optional blocks (recorded under
    ``truncated``) instead of emitting a line the runner would truncate
    into non-JSON — the BENCH_r05 ``"parsed": null`` regression class."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    try:
        import bench
    finally:
        sys.path.pop(0)
    record = {"metric": "m", "value": 1.5, "unit": "tok/s",
              "vs_baseline": 0.5,
              "detail": {"mfu": 0.4, "backend": "cpu",
                         "metrics": {"filler": "x" * 4000}}}
    line = bench.summary_lines(record, None)[-1]
    assert len(line) <= bench.BENCH_SUMMARY_MAX_CHARS
    parsed = json.loads(line)
    assert parsed["truncated"] == ["train_metrics"]
    assert parsed["metric"] == "m"       # headline survives the cap


def test_bench_emit_contract_subprocess():
    """THE handshake pin: run bench.py in emit-only mode as a REAL
    subprocess and assert the literal last stdout line is the parseable
    bare summary (flushed, nothing after it), with the prefixed twin
    directly above."""
    import subprocess

    root = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        "..", ".."))
    env = dict(os.environ, DSTPU_BENCH_EMIT_ONLY="1", JAX_PLATFORMS="cpu",
               DS_ACCELERATOR="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py")], env=env,
        capture_output=True, text=True, timeout=300, cwd=root)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert proc.stdout.endswith("\n")
    lines = proc.stdout.rstrip("\n").split("\n")
    last = lines[-1]
    parsed = json.loads(last)            # the runner's exact read
    assert parsed["metric"] == "emit_selftest"
    assert len(last) <= 1800
    assert lines[-2] == "BENCH_JSON: " + last
    json.loads(lines[-3])                # the full record line parses too


def test_metrics_dump_serving_prefix_hit_ratio_line():
    """--serving renders the prefix-cache hit-ratio line from the
    ds_serve_prefix_* series (and omits it when the cache never ran)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    m = {"ds_serve_kv_pages_used": 6, "ds_serve_kv_pages_free": 2,
         "ds_serve_preempted_total": 1,
         "ds_serve_prefix_hit_tokens_total": 300,
         "ds_serve_prefix_miss_tokens_total": 100,
         "ds_serve_prefix_cache_pages": 7,
         "ds_serve_prefix_evictions_total": 2}
    out = metrics_dump.serving_kv_summary(m)
    assert "kv pages: 6 used / 2 free (8 total)" in out
    assert "prefix cache: 75.0% hit ratio (300 hit / 100 computed" in out
    assert "7 cached pages" in out and "2 evictions" in out
    # cache never ran (off or fixed-slot): no prefix line at all
    cold = metrics_dump.serving_kv_summary(
        {"ds_serve_kv_pages_used": 1, "ds_serve_kv_pages_free": 7})
    assert "prefix cache" not in cold and "host tier" not in cold
    # host tier ran: one line with resident/demoted/promoted counts
    tier = metrics_dump.serving_kv_summary(
        {**m, "ds_serve_kv_host_pages": 3, "ds_serve_kv_demote_total": 9,
         "ds_serve_kv_promote_total": 6})
    assert "kv host tier: 3 pages resident, 9 demoted, 6 promoted" in tier


def test_metrics_dump_offload_relay_line():
    """--comms renders the offload relay one-liner from ds_offload_*
    (and nothing when the offload path never ran)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    m = {"ds_offload_relay_bytes_total": {'{dir="h2d"}': 3 * 2**20,
                                          '{dir="d2h"}': 2**20},
         "ds_offload_prefetch_hits_total": 30,
         "ds_offload_prefetch_misses_total": 10,
         "ds_offload_relay_seconds": {"count": 40, "sum": 0.25}}
    line = metrics_dump.offload_relay_line(m)
    assert "3.00 MiB h2d / 1.00 MiB d2h" in line
    assert "prefetch 75% hit (30/40)" in line
    assert "0.25s stalled" in line
    assert metrics_dump.offload_relay_line({}) == ""
    assert metrics_dump.offload_relay_line(
        {"ds_offload_relay_bytes_total": {}}) == ""


def test_metrics_dump_renders_snapshot_and_csv(tmp_path):
    """tools/metrics_dump.py renders /statz snapshots and csvMonitor dirs
    as terminal tables (stdlib-only; used against live ports in ops)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        import metrics_dump
    finally:
        sys.path.pop(0)
    reg = MetricsRegistry().enable()
    reg.counter("ds_t_reqs_total").inc(5)
    reg.histogram("ds_t_lat_seconds", buckets=(1.0, 2.0)).record(0.5)
    reg.counter("ds_t_finished_total", labels={"reason": "eos"}).inc(2)
    snap = tmp_path / "statz.json"
    snap.write_text(reg.statz_json())
    table = metrics_dump.render(metrics_dump.rows_from_snapshot(
        metrics_dump.load_snapshot(str(snap))))
    assert "ds_t_reqs_total" in table and "5" in table
    assert 'ds_t_finished_total{reason="eos"}' in table
    # csvMonitor dir: last value per series
    mon = tmp_path / "mon"
    mon.mkdir()
    (mon / "Train_loss.csv").write_text("step,Train/loss\n1,2.5\n2,2.25\n")
    table = metrics_dump.render(metrics_dump.rows_from_snapshot(
        metrics_dump.load_snapshot(str(mon))))
    assert "Train_loss" in table and "2.25 @ step 2" in table

    # --comms overlap on/off indicator (docs/OBSERVABILITY.md "Overlap")
    assert metrics_dump.overlap_line({}) == \
        "overlap: off (GSPMD-placed collectives)"
    line = metrics_dump.overlap_line({"ds_overlap_buckets": 4.0,
                                      "ds_overlap_hidden_comm_seconds_est":
                                      0.0})
    assert line == "overlap: on (4 buckets, no device capture yet)"
    # a capture that MEASURED zero hidden comm is not "no capture"
    line = metrics_dump.overlap_line({"ds_overlap_buckets": 4.0,
                                      "ds_overlap_hidden_comm_seconds_est":
                                      0.0,
                                      "ds_profile_window_seconds": 1.5})
    assert line == "overlap: on (4 buckets, 0s comm hidden in last capture)"
    line = metrics_dump.overlap_line({"ds_overlap_buckets": 4.0,
                                      "ds_overlap_hidden_comm_seconds_est":
                                      0.0125})
    assert "overlap: on (4 buckets" in line and "0.0125s/step" in line
    # csvMonitor-directory snapshots carry {"last": ...} series dicts
    line = metrics_dump.overlap_line(
        {"ds_overlap_buckets": {"last": 4.0, "step": 3, "events": 3},
         "ds_overlap_hidden_comm_seconds_est": {"last": 0.0125, "step": 3,
                                                "events": 3}})
    assert "overlap: on (4 buckets" in line and "0.0125s/step" in line


# ---------------------------------------------------------------------------
# tier-1 namespace guard
# ---------------------------------------------------------------------------

_DOC = os.path.join(os.path.dirname(__file__), "..", "..", "docs",
                    "OBSERVABILITY.md")


def test_namespace_guard_all_metrics_documented(devices):
    """Fails the suite if ANY registered metric leaves the ``ds_``
    namespace or is missing from docs/OBSERVABILITY.md (docs drift =
    red).  Registers the full engine surface first so the guard holds
    regardless of test order."""
    from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.comms import comm_metrics
    from deepspeed_tpu.monitor.memory import MemoryTelemetry
    from deepspeed_tpu.profiling.flops import TrainFlopsMeter
    from deepspeed_tpu.serving.engine import ServingEngine
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    # instantiate every instrument owner (no weights/compiles needed)
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=1, hidden_size=32,
                      intermediate_size=64, num_heads=2, num_kv_heads=1,
                      vocab_size=64, remat=False)
    InferenceEngine(model, DeepSpeedInferenceConfig(dtype="float32"))
    ServingEngine(model, {"dtype": "float32", "max_out_tokens": 32},
                  num_slots=1)
    timers = SynchronizedWallClockTimer()
    for n in (timers.FORWARD, timers.BACKWARD, timers.STEP, timers.BATCH):
        timers(n)
    # PR 3 families: the full comm-op instrument surface, HBM gauges, and
    # the FLOPs/MFU gauges — all must be documented too (guard EXTENDED,
    # not weakened)
    comm_metrics.ensure_registered()
    MemoryTelemetry()
    TrainFlopsMeter()
    # ISSUE 5 device-truth families: the ds_profile_* gauges and every
    # ds_comm_<op>_device_* series must be documented too
    from deepspeed_tpu.profiling import device_trace

    device_trace.ensure_registered(get_registry())
    # ISSUE 20 families: the continuous-profiler ds_prof_* window gauges
    # and counters (the labeled scope/regression series register at first
    # use with labels, exercised by tests/unit/test_continuous_profiler)
    from deepspeed_tpu.profiling import continuous

    continuous.ensure_registered(get_registry())
    get_registry().gauge("ds_prof_scope_device_seconds",
                         labels={"scope": "fwd_bwd"}).set(0.0)
    get_registry().counter("ds_prof_regressions_total",
                           labels={"scope": "comm"})
    # ISSUE 7 families: the per-request phase-attribution histograms
    # (registered at tracer construction) and the training-numerics
    # step gauges (registered lazily at the optimizer boundary, so the
    # guard registers them explicitly here)
    from deepspeed_tpu.monitor.request_trace import PHASES, \
        get_request_tracer
    from deepspeed_tpu.runtime.engine import TRAIN_STEP_GAUGES

    get_request_tracer()
    for _n, _h in TRAIN_STEP_GAUGES.items():
        get_registry().gauge(_n, _h)

    with open(_DOC) as fh:
        documented = set(re.findall(r"ds_[a-z0-9_]+", fh.read()))
    # every phase in the edge partition must have its histogram
    # documented BY NAME (not as a pattern): the fleet/router consumers
    # key on the exact series names
    for _p in PHASES:
        assert f"ds_serve_phase_{_p}_seconds" in documented, (
            f"ds_serve_phase_{_p}_seconds is part of the request-span "
            f"edge partition but is not documented in "
            f"docs/OBSERVABILITY.md")
    name_re = re.compile(r"^ds_[a-z0-9_]+$")
    train_re = re.compile(r"^ds_train_[a-z0-9_]+_seconds$")
    # ds_comm_<op>_<suffix>: the suffix schema is documented as a table;
    # every OP SLUG must additionally appear in the documented op list
    # (written there as `ds_comm_<op>_` tokens).  The device-truth
    # suffixes (_device_seconds / _device_busbw_gbps) are part of the
    # schema and additionally require their suffix token documented —
    # no blanket exemption for the new family.
    comm_re = re.compile(r"^ds_comm_([a-z0-9_]+?)_"
                         r"(calls_total|bytes_total|dense_bytes_total|"
                         r"seconds|algbw_gbps|"
                         r"busbw_gbps|device_seconds|device_busbw_gbps)$")
    # the quantized dense-twin suffix is part of the schema: its name must
    # be documented like the device-truth suffixes (guard extended)
    assert any(d.endswith("dense_bytes_total") for d in documented), (
        "the ds_comm_*_dense_bytes_total schema is registered but no "
        "*_dense_bytes_total name is documented in docs/OBSERVABILITY.md")
    for suffix in ("device_seconds", "device_busbw_gbps"):
        assert any(d.endswith(suffix) for d in documented), (
            f"the ds_comm_*_{suffix} schema is registered but no "
            f"*_{suffix} name is documented in docs/OBSERVABILITY.md")
    names = get_registry().names()
    assert names, "no metrics registered — instrumentation went missing?"
    bad_ns = [n for n in names if not name_re.match(n)]
    assert not bad_ns, f"metrics outside the ds_ namespace: {bad_ns}"
    undoc = []
    for n in names:
        if train_re.match(n):
            continue
        m = comm_re.match(n)
        if m:
            if f"ds_comm_{m.group(1)}_" not in documented:
                undoc.append(n)
            continue
        if n not in documented:
            undoc.append(n)
    assert not undoc, (f"metrics not documented in docs/OBSERVABILITY.md: "
                       f"{undoc} (the ds_train_*_seconds family is exempt "
                       f"— it is documented as a pattern; ds_comm op slugs "
                       f"must appear in the documented op list)")
