"""Checkpoint tooling tests (reference analog: tests/unit/checkpoint/,
SURVEY.md §4 — save/load across topologies, zero_to_fp32, universal)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint import (DeepSpeedCheckpoint, ds_to_universal,
                                      load_universal_params)
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.utils import (list_param_paths, safe_get_full_fp32_param,
                                 safe_get_full_grad,
                                 safe_get_full_optimizer_state,
                                 safe_set_full_fp32_param)
from deepspeed_tpu.utils.zero_to_fp32 import (
    convert_zero_checkpoint_to_fp32_state_dict,
    get_fp32_state_dict_from_zero_checkpoint)


def _make_engine(devices, rng, stage=3, tp=1, fsdp=None, tag_batch=8):
    fsdp = fsdp or (8 // tp)
    mesh = build_mesh(fsdp=fsdp, tp=tp, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256)
    ds = {"train_batch_size": tag_batch, "gradient_accumulation_steps": 1,
          "zero_optimization": {"stage": stage},
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds, mesh=mesh)
    toks = jax.random.randint(rng, (tag_batch, 32), 0, 256)
    loss = engine.forward((toks, toks))
    engine.backward(loss)
    engine.step()
    return engine, toks


def test_zero_to_fp32_consolidation(devices, rng, tmp_path):
    engine, _ = _make_engine(devices, rng, stage=3)
    engine.save_checkpoint(str(tmp_path))
    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    assert "layers/attn/wq" in sd
    assert sd["layers/attn/wq"].dtype == np.float32
    np.testing.assert_allclose(
        sd["layers/attn/wq"],
        np.asarray(jax.device_get(engine.state.params["layers"]["attn"]["wq"])),
        rtol=1e-6)
    out = convert_zero_checkpoint_to_fp32_state_dict(
        str(tmp_path), str(tmp_path / "fp32_model"))
    loaded = np.load(out)
    assert "final_norm/scale" in loaded


def test_save_stage3_load_stage0_topology_change(devices, rng, tmp_path):
    """Reference matrix: save at stage X / world A, load at stage Y / world B."""
    engine, toks = _make_engine(devices, rng, stage=3, tp=1)
    engine.save_checkpoint(str(tmp_path))
    ref = np.asarray(jax.device_get(engine.state.params["layers"]["mlp"]["w_up"]))

    engine2, _ = _make_engine(devices, rng, stage=0, tp=2)
    engine2.load_checkpoint(str(tmp_path))
    got = np.asarray(jax.device_get(engine2.state.params["layers"]["mlp"]["w_up"]))
    np.testing.assert_array_equal(ref, got)


def test_universal_checkpoint_roundtrip(devices, rng, tmp_path):
    engine, _ = _make_engine(devices, rng, stage=1)
    engine.save_checkpoint(str(tmp_path / "native"))
    udir = ds_to_universal(str(tmp_path / "native"), str(tmp_path / "universal"),
                           split_layers=True)
    ck = DeepSpeedCheckpoint(str(tmp_path / "native"))
    assert ck.zero_stage == 1

    target = jax.device_get(engine.state.params)
    rebuilt = load_universal_params(udir, target)
    for a, b in zip(jax.tree.leaves(rebuilt), jax.tree.leaves(target)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # Optimizer states (exp_avg/exp_avg_sq/step) roundtrip too, so a universal
    # checkpoint is a training-resume checkpoint, not params-only.
    from deepspeed_tpu.checkpoint import load_universal_optim

    opt_target = jax.device_get({"opt_state": engine.state.opt_state})
    rebuilt_opt = load_universal_optim(udir, opt_target)
    for a, b in zip(jax.tree.leaves(rebuilt_opt), jax.tree.leaves(opt_target)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_tensor_fragment_api(devices, rng):
    engine, _ = _make_engine(devices, rng, stage=3)
    paths = list_param_paths(engine.state.params)
    assert "layers/attn/wq" in paths

    w = safe_get_full_fp32_param(engine, "layers/attn/wq")
    assert w.dtype == np.float32 and w.shape == (2, 64, 64)

    g = safe_get_full_grad(engine, "layers/attn/wq")
    assert g.shape == w.shape  # accumulator exists (zeroed after step)

    m = safe_get_full_optimizer_state(engine, "layers/attn/wq", "exp_avg")
    assert m.shape == w.shape
    assert np.abs(m).sum() > 0  # one step taken -> nonzero first moment

    new = np.zeros_like(w)
    safe_set_full_fp32_param(engine, "layers/attn/wq", new)
    np.testing.assert_array_equal(
        safe_get_full_fp32_param(engine, "layers/attn/wq"), new)
