"""Comm-layer quantized transport tests (ISSUE 15 tentpole;
comm/collectives_q.py).

Covers: parity of every quantized collective against its dense twin on
the 8-device mesh (all-reduce / all-gather incl. the tiled-dim form /
reduce-scatter incl. the scatter-dim form / all-to-all over both ulysses-
and MoE-shaped splits), the error-feedback accumulation contract (with a
carried residual the T-step accumulated all-reduce error stays BOUNDED;
without it the per-step rounding bias accumulates and the mean error is
measurably worse — the deterministic form of "compressed grad all-reduce
converges"), the double byte ledger (wire bytes by dtype + the
dense-twin series on ONE trace), the ZeRO++ seam regression (qwAG/qgRS
through the refactored thin wrappers are numerically IDENTICAL to a
straight-line reference over the shared comm/quant.py codec), and the
ring-carry form (quantize once, rotate codes, one quantization error
total).
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import collectives_q as cq
from deepspeed_tpu.comm.mesh import build_mesh
from deepspeed_tpu.comm.quant import dequantize_blockwise, quantize_blockwise
from deepspeed_tpu.monitor.comms import CommMetrics
from deepspeed_tpu.monitor.metrics import MetricsRegistry


@pytest.fixture()
def dp_mesh(devices):
    return build_mesh(dp=8, devices=devices)


def _sm(mesh, f, ins, outs):
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=ins,
                                 out_specs=outs, check_vma=False))


# ---------------------------------------------------------------------------
# parity vs dense twins
# ---------------------------------------------------------------------------

def test_q_all_reduce_matches_mean(dp_mesh, rng):
    x = jax.random.normal(rng, (8, 1000)).astype(jnp.float32)

    def body(xl):
        out, res = cq.q_all_reduce(xl[0], "dp",
                                   residual=jnp.zeros_like(xl[0]))
        return out[None], res[None]

    out, res = _sm(dp_mesh, body, P("dp"), (P("dp"), P("dp")))(x)
    want = np.asarray(x).mean(axis=0)
    got = np.asarray(out)
    # two quantizations (worker + reduced phase): ~2 code steps of error
    tol = 2 * float(np.abs(np.asarray(x)).max()) / 127 + 1e-6
    np.testing.assert_allclose(got[0], want, atol=tol)
    for r in range(8):   # the reduced value is truly replicated
        np.testing.assert_array_equal(got[r], got[0])
    # residual = what quantization dropped; nonzero for generic values
    assert float(np.abs(np.asarray(res)).sum()) > 0


def test_q_all_reduce_sum_and_no_residual(dp_mesh, rng):
    x = jax.random.normal(rng, (8, 512)).astype(jnp.float32)

    def body(xl):
        out, res = cq.q_all_reduce(xl[0], "dp", mean=False)
        assert res is None
        return out[None]

    out = _sm(dp_mesh, body, P("dp"), P("dp"))(x)
    want = np.asarray(x).sum(axis=0)
    tol = 8 * 2 * float(np.abs(np.asarray(x)).max()) / 127 + 1e-5
    np.testing.assert_allclose(np.asarray(out)[0], want, atol=tol)


def test_q_all_gather_dim_matches_dense(dp_mesh, rng):
    xd = jax.random.normal(rng, (4, 16, 8))

    def body(xl):
        return cq.q_all_gather_dim(xl, "dp", 1)

    out = _sm(dp_mesh, body, P(None, "dp", None), P(None, None, None))(xd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xd),
                               atol=float(jnp.abs(xd).max()) / 127 + 1e-6)


def test_q_reduce_scatter_dim_matches_psum_scatter(dp_mesh, rng):
    xs = jax.random.normal(rng, (8, 4, 16))

    def body(xl):
        q = cq.q_reduce_scatter_dim(xl[0], "dp", 1)
        d = jax.lax.psum_scatter(xl[0], "dp", scatter_dimension=1,
                                 tiled=True)
        return q[None], d[None]

    qv, dv = _sm(dp_mesh, body, P("dp"), (P("dp"), P("dp")))(xs)
    tol = 8 * float(np.abs(np.asarray(xs)).max()) / 127 + 1e-5
    np.testing.assert_allclose(np.asarray(qv), np.asarray(dv), atol=tol)


@pytest.mark.parametrize("split,concat,shape,spec", [
    (1, 2, (2, 8, 16, 4), P(None, None, "dp", None)),   # ulysses reshard
    (0, 0, (16, 64, 6), P(None, "dp")),                 # MoE dispatch
])
def test_q_all_to_all_matches_dense(dp_mesh, rng, split, concat, shape,
                                    spec):
    x = jax.random.normal(rng, shape)

    def body(xl):
        d = jax.lax.all_to_all(xl, "dp", split_axis=split,
                               concat_axis=concat, tiled=True)
        q = cq.q_all_to_all(xl, "dp", split, concat)
        return d, q

    # both cases keep the sharded dim in place (it IS the concat dim for
    # the ulysses case and untouched for the MoE case)
    dv, qv = _sm(dp_mesh, body, spec, (spec, spec))(x)
    np.testing.assert_allclose(
        np.asarray(qv), np.asarray(dv),
        atol=float(np.abs(np.asarray(x)).max()) / 127 + 1e-5)


def test_ring_carry_roundtrip_and_losslessness(rng):
    """The sequence-ring form: quantize once, rotate codes — and
    re-quantizing a dequantized block is lossless, so the ring pays ONE
    quantization error no matter how many hops."""
    x = jax.random.normal(rng, (2, 4, 8, 16))
    carry = cq.quantize_carry(x)
    assert carry["q"].dtype == jnp.int8
    back = cq.dequantize_carry(carry, x.shape, x.dtype)
    tol = float(jnp.abs(x).max()) / 127 + 1e-6
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=tol)
    # lossless requantization: codes of the dequantized value are the codes
    again = cq.quantize_carry(back)
    np.testing.assert_array_equal(np.asarray(again["q"]),
                                  np.asarray(carry["q"]))


# ---------------------------------------------------------------------------
# error feedback: bounded vs accumulating bias
# ---------------------------------------------------------------------------

def test_error_feedback_bounds_accumulated_error(dp_mesh):
    """THE convergence contract, in its deterministic form: all-reduce the
    SAME per-rank gradients T times and accumulate the outputs (what an
    optimizer integrates).  With the carried residual the accumulated
    mean's error stays bounded by ~one quantization step (errors cancel
    across steps); residual-off re-commits the identical rounding bias
    every step, so the mean error stays at the full single-shot bias —
    measurably (here >=4x) worse.  This is why
    ``comm_quantization.error_feedback`` defaults ON."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 2048))
                    * (1 + 10 * rng.random((8, 2048))), jnp.float32)
    true_mean = np.asarray(x).mean(axis=0)
    T = 32

    def roll(ef):
        def body(xl):
            def step(carry, _):
                acc, res = carry
                out, new_res = cq.q_all_reduce(
                    xl[0], "dp", residual=(res if ef else None))
                return (acc + out, new_res if ef else res), None

            (acc, _), _ = jax.lax.scan(
                step, (jnp.zeros_like(xl[0]), jnp.zeros_like(xl[0])),
                jnp.arange(T))
            return acc[None]

        acc = _sm(dp_mesh, body, P("dp"), P("dp"))(x)
        return float(np.abs(np.asarray(acc[0]) / T - true_mean).max())

    err_ef = roll(True)
    err_no = roll(False)
    assert err_no >= 4 * err_ef, (err_ef, err_no)
    # and the compensated accumulation is genuinely tight: well under one
    # single-shot quantization step
    single_step = 2 * float(np.abs(np.asarray(x)).max()) / 127
    assert err_ef < single_step, (err_ef, single_step)


# ---------------------------------------------------------------------------
# byte ledger: wire + dense twin on one trace
# ---------------------------------------------------------------------------

def test_record_q_double_ledger(dp_mesh, rng):
    reg = MetricsRegistry().enable()
    cm = CommMetrics(registry=reg)
    cm.configure(enabled=True)
    import deepspeed_tpu.comm.collectives_q as mod
    orig = mod.comm_metrics
    mod.comm_metrics = cm
    try:
        x = jax.random.normal(rng, (8, 4096)).astype(jnp.float32)

        def body(xl):
            out, _ = cq.q_all_reduce(xl[0], "dp")
            return out[None]

        # eval_shape traces without compiling — trace-time records fire
        jax.eval_shape(
            jax.shard_map(body, mesh=dp_mesh, in_specs=P("dp"),
                          out_specs=P("dp"), check_vma=False), x)
    finally:
        mod.comm_metrics = orig
    import json as _json

    metrics = _json.loads(reg.statz_json())["metrics"]

    def fam(name):
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return sum(x for x in v.values() if isinstance(x, (int, float)))
        return v or 0

    wire = fam("ds_comm_q_all_reduce_bytes_total")
    dense = fam("ds_comm_q_all_reduce_dense_bytes_total")
    assert dense == 4096 * 4                       # fp32 local grad
    assert 0 < wire < 0.35 * dense, (wire, dense)  # ~2-4x fewer wire bytes
    # the back-compat trace dicts count the call once
    assert sum(v for k, v in cm.counts.items()
               if "q_all_reduce" in k) == 1


# ---------------------------------------------------------------------------
# ZeRO++ seam regression: thin wrappers == straight-line codec reference
# ---------------------------------------------------------------------------

def test_zeropp_seam_preserves_qwag_numerics(devices, rng):
    """qwAG through the refactored seam (zeropp.q_all_gather_flat ->
    collectives_q) is numerically IDENTICAL to quantizing each rank's
    shard with the shared codec and concatenating the dequantized parts —
    the refactor moved code, not math."""
    from deepspeed_tpu.runtime.zero import zeropp as zpp

    mesh = build_mesh(fsdp=8, devices=devices)
    x = jax.random.normal(rng, (8, 640)).astype(jnp.float32)

    def body(xl):
        return zpp.q_all_gather_flat(xl[0], "fsdp")[None]

    got = np.asarray(_sm(mesh, body, P("fsdp"), P("fsdp"))(x))[0]
    # straight-line reference over the SAME codec (atol = float32 ulp:
    # XLA fuses the q*s dequant differently in- vs out-of-jit)
    parts = []
    for r in range(8):
        q, s = quantize_blockwise(x[r])
        parts.append(np.asarray(dequantize_blockwise(q, s, (640,))))
    np.testing.assert_allclose(got, np.concatenate(parts), rtol=0,
                               atol=1e-6)


def test_zeropp_seam_preserves_qgrs_numerics(devices, rng):
    """qgRS through the refactored seam (zeropp.reduce_scatter_flat
    quantized -> collectives_q.q_reduce_scatter_flat): each destination
    shard quantized separately, summed in fp32 after dequant — identical
    to the straight-line reference."""
    from deepspeed_tpu.runtime.zero import zeropp as zpp

    mesh = build_mesh(fsdp=8, devices=devices)
    n_pad = 8 * 512
    xs = jax.random.normal(rng, (8, n_pad)).astype(jnp.float32)

    def body(xl):
        return zpp.reduce_scatter_flat(xl[0], "fsdp", True)[None]

    got = np.asarray(_sm(mesh, body, P("fsdp"), P("fsdp"))(xs))
    xs_np = np.asarray(xs)
    for r in range(8):
        want = np.zeros(512, np.float32)
        for src in range(8):
            chunk = xs_np[src].reshape(8, 512)[r]
            q, s = quantize_blockwise(jnp.asarray(chunk))
            want += np.asarray(dequantize_blockwise(q, s, (512,)))
        np.testing.assert_allclose(got[r], want, rtol=0, atol=1e-5)
