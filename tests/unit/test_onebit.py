"""1-bit Adam/LAMB engine tests (VERDICT r2 item 6 done-criteria):
convergence parity vs dense Adam on the 8-device mesh + comm volume
reduction via CommsLogger.
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.comm import comm as comm_api
from tests.unit.simple_model import SimpleModel, random_dataset


def _train(opt_type, steps=12, freeze_step=100, lr=5e-2, **opt_params):
    x, y = random_dataset(n=64)
    cfg = {"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 1,
           "comms_logger": {"enabled": comm_api.comms_logger.enabled},
           "optimizer": {"type": opt_type,
                         "params": {"lr": lr, "freeze_step": freeze_step,
                                    **opt_params}}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, rng=jax.random.PRNGKey(11))
    losses = []
    for i in range(steps):
        lo = i * 16 % 48
        loss = engine.forward((x[lo:lo + 16], y[lo:lo + 16]))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


def test_warmup_matches_dense_adam():
    """With freeze_step > steps the 1-bit path is exactly dense Adam."""
    dense, _ = _train("Adam", steps=8, adam_w_mode=False)
    onebit, _ = _train("OneBitAdam", steps=8, freeze_step=100)
    np.testing.assert_allclose(dense, onebit, rtol=2e-4, atol=2e-5)


def test_compression_stage_converges():
    """After freeze_step the compressed exchange still trains the model.

    Like the reference, 1-bit Adam needs enough warmup that the frozen
    variance is meaningful, and a gentler lr in the compression stage (the
    sign-compressed momentum behaves like signSGD per coordinate)."""
    # eps floors the frozen-variance denominator: sign-compressed momentum is
    # dense, so coordinates with ~zero variance would otherwise blow up
    # (inherent to the algorithm; the reference exposes eps the same way)
    losses, engine = _train("OneBitAdam", steps=30, freeze_step=15, lr=1e-3,
                            eps=1e-3)
    assert engine.global_steps == 30
    assert np.isfinite(losses).all(), losses
    assert min(losses[15:]) < losses[0], losses


def test_onebit_lamb_trains():
    losses, _ = _train("OneBitLamb", steps=16, freeze_step=8, lr=5e-3, eps=1e-3)
    assert min(losses[8:]) < losses[0], losses
    assert np.isfinite(losses[-1]), losses


def test_comm_volume_reduced():
    comm_api.comms_logger.configure(enabled=True)
    comm_api.comms_logger.reset()
    _train("OneBitAdam", steps=6, freeze_step=2, lr=1e-3)
    recs = comm_api.comms_logger.bytes
    comp = sum(v for k, v in recs.items() if "compressed" in k)
    assert comp > 0, recs
    # payload per exchanged element must be ~1 bit, not 16:
    # 4 compressed steps x n_params elements -> bytes ~ steps * n / 8 (x2 legs)
    n_params = sum(p.size for p in [np.zeros((8, 16)), np.zeros((16,)),
                                    np.zeros((16, 4)), np.zeros((4,))])
    dense_equiv = 4 * n_params * 2  # bf16 bytes for the same exchanges
    assert comp < dense_equiv / 2, (comp, dense_equiv)
    comm_api.comms_logger.configure(enabled=False)
    comm_api.comms_logger.reset()


def test_rejects_zero2_and_fp16():
    cfg_base = {"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-2}}}
    with pytest.raises(ValueError, match="ZeRO"):
        deepspeed_tpu.initialize(model=SimpleModel(16),
                                 config={**cfg_base, "zero_optimization": {"stage": 2}})
    with pytest.raises(ValueError, match="fp16|bf16"):
        deepspeed_tpu.initialize(model=SimpleModel(16),
                                 config={**cfg_base, "fp16": {"enabled": True}})


# ---------------------------------------------------------------------------
# 0/1 Adam (real local-step schedule; VERDICT r3 item 8)
# ---------------------------------------------------------------------------

def test_zoadam_warmup_matches_dense_adam():
    """While the variance adapts every step (var_update_scaler=1) and is not
    yet frozen, 0/1 Adam is exactly dense Adam."""
    dense, _ = _train("Adam", steps=8, adam_w_mode=False)
    zo, engine = _train("ZeroOneAdam", steps=8, var_freeze_step=100,
                        var_update_scaler=1)
    np.testing.assert_allclose(dense, zo, rtol=2e-4, atol=2e-5)
    assert engine._onebit_stacked


def test_zoadam_local_steps_converge():
    """After the variance freezes, communication-skipping local steps with
    compressed reconciliation still train the model."""
    losses, engine = _train("ZeroOneAdam", steps=40, var_freeze_step=10,
                            var_update_scaler=2, local_step_clipper=4,
                            local_step_scaler=1, lr=1e-3, eps=1e-3)
    assert engine.global_steps == 40
    assert np.isfinite(losses).all(), losses
    assert min(losses[10:]) < losses[0], losses


def test_zoadam_replicas_reconcile_at_sync():
    """Replicas diverge during local steps and become bit-identical again at
    each sync step (sign-compressed displacement exchange)."""
    def replicas_equal(engine):
        eq = True
        for leaf in jax.tree.leaves(jax.device_get(engine.state.params)):
            eq &= all(np.array_equal(leaf[0], leaf[i])
                      for i in range(1, leaf.shape[0]))
        return eq

    # schedule: steps 1-4 warm (synced), step 5 sync, interval->2,
    # step 6 local (diverged), step 7 sync (reconciled)
    _, engine6 = _train("ZeroOneAdam", steps=6, var_freeze_step=4,
                        var_update_scaler=1, local_step_clipper=2,
                        local_step_scaler=1, lr=1e-3)
    assert not replicas_equal(engine6), "replicas should diverge locally"
    _, engine7 = _train("ZeroOneAdam", steps=7, var_freeze_step=4,
                        var_update_scaler=1, local_step_clipper=2,
                        local_step_scaler=1, lr=1e-3)
    assert replicas_equal(engine7), "sync step must reconcile replicas"


def test_zoadam_comm_skipped_on_local_steps():
    """Local steps execute no sync exchange: 0/1 Adam's whole point.  The
    CommsLogger counts at trace time (the sync sits in a lax.cond branch),
    so assert on the state's executed-sync counter instead."""
    def executed_syncs(clipper, scaler=1):
        _, engine = _train("ZeroOneAdam", steps=20, var_freeze_step=4,
                           var_update_scaler=1, local_step_clipper=clipper,
                           local_step_scaler=scaler, lr=1e-3)
        return int(jax.device_get(engine.state.opt_state.syncs))

    # scaler=1 -> interval doubles at every stable sync (constant LR):
    # clipper=1: all 20 steps sync (4 warm + 16 frozen at interval 1);
    # clipper=8: 4 warm + frozen syncs at steps 5,7,11,19 = 8 total
    assert executed_syncs(1) == 20
    assert executed_syncs(8) == 8
    # reference-default scaler (32678): growth never triggers in 20 steps,
    # so every frozen step syncs at interval 1
    assert executed_syncs(8, scaler=32678) == 20


def test_zoadam_lr_policy_resets_interval():
    """An LR change at a sync resets the local-step interval to 1 (reference
    local_step_scaler LR-tracking policy; VERDICT r4 item 9)."""
    import deepspeed_tpu
    from tests.unit.simple_model import SimpleModel, random_dataset

    x, y = random_dataset(n=64)

    def run(schedule):
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "ZeroOneAdam",
                             "params": {"lr": 1e-3, "var_freeze_step": 4,
                                        "var_update_scaler": 1,
                                        "local_step_clipper": 8,
                                        "local_step_scaler": 1}}}
        if schedule:
            cfg["scheduler"] = schedule
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16), config=cfg,
            rng=jax.random.PRNGKey(11))
        for i in range(14):
            lo = i * 16 % 48
            loss = engine.forward((x[lo:lo + 16], y[lo:lo + 16]))
            engine.backward(loss)
            engine.step()
        return engine

    # constant LR: syncs at 5,7,11 then next at 19 -> interval has grown to 8
    const = run(None)
    assert int(jax.device_get(const.state.opt_state.sync_interval)) == 8
    # stepwise-decaying LR (changes every step): every sync sees a changed
    # LR, so the interval stays pinned at 1 and every frozen step syncs
    decay = run({"type": "WarmupDecayLR",
                 "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-3,
                            "warmup_num_steps": 2, "total_num_steps": 64}})
    assert int(jax.device_get(decay.state.opt_state.sync_interval)) == 1
    assert int(jax.device_get(decay.state.opt_state.syncs)) == 14


def test_onebit_rejects_gradient_clipping():
    """gradient_clipping + 1-bit optimizer is a hard error (VERDICT r4 weak
    #5: the old one-shot warning was too easy to miss)."""
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_clipping": 1.0,
           "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-2}}}
    with pytest.raises(ValueError, match="gradient_clipping"):
        deepspeed_tpu.initialize(model=SimpleModel(16), config=cfg)


def test_zoadam_gathered_parameters_model_shaped():
    """GatheredParameters over a 0/1 Adam engine exposes model-shaped leaves
    (no [W] replica axis) and a write lands on every replica."""
    from deepspeed_tpu.runtime.zero.partition_parameters import GatheredParameters

    _, engine = _train("ZeroOneAdam", steps=2, var_freeze_step=100,
                       var_update_scaler=1)
    stacked_shapes = [l.shape for l in jax.tree.leaves(engine.state.params)]
    with GatheredParameters(engine=engine) as p:
        for leaf, st in zip(jax.tree.leaves(p), stacked_shapes):
            assert leaf.shape == st[1:], (leaf.shape, st)
        jax.tree.leaves(p)[0][:] = 0.0
    first = np.asarray(jax.device_get(jax.tree.leaves(engine.state.params)[0]),
                       np.float32)
    assert (first == 0).all(), "write must reach every worker replica"
