"""Tiny model fixtures (reference: ``tests/unit/simple_model.py``, SURVEY.md §4)."""

import flax.linen as nn
import jax.numpy as jnp


class SimpleModel(nn.Module):
    """MLP that computes its own loss, matching the engine contract
    (forward returns scalar loss, as the reference's engine expects)."""

    hidden_dim: int = 16
    nlayers: int = 2

    @nn.compact
    def __call__(self, x, y):
        h = x
        for _ in range(self.nlayers):
            h = nn.Dense(self.hidden_dim)(h)
            h = nn.relu(h)
        out = nn.Dense(y.shape[-1] if y.ndim > 1 else 1)(h)
        if y.ndim == 1:
            y = y[:, None]
        return jnp.mean((out - y) ** 2)


class SimpleClassifier(nn.Module):
    hidden_dim: int = 32
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, labels):
        h = nn.Dense(self.hidden_dim)(x)
        h = nn.relu(h)
        logits = nn.Dense(self.num_classes)(h)
        logp = nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def random_dataset(n=64, dim=8, out_dim=4, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    w = rng.normal(size=(dim, out_dim))
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(n, out_dim))).astype(np.float32)
    return x, y
