"""Config-system tests (reference test model: tests/unit/runtime/test_ds_config*.py,
SURVEY.md §4)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, resolve_batch_triad


class TestBatchTriad:
    def test_all_given_consistent(self):
        assert resolve_batch_triad(32, 2, 2, 8) == (32, 2, 2)

    def test_all_given_inconsistent(self):
        with pytest.raises(ValueError):
            resolve_batch_triad(33, 2, 2, 8)

    def test_infer_train_batch(self):
        assert resolve_batch_triad(None, 2, 2, 8) == (32, 2, 2)

    def test_infer_micro_batch(self):
        assert resolve_batch_triad(32, None, 2, 8) == (32, 2, 2)

    def test_infer_grad_accum(self):
        assert resolve_batch_triad(32, 2, None, 8) == (32, 2, 2)

    def test_only_train_batch(self):
        assert resolve_batch_triad(16, None, None, 8) == (16, 2, 1)

    def test_nothing(self):
        assert resolve_batch_triad(None, None, None, 8) == (8, 1, 1)

    def test_indivisible(self):
        with pytest.raises(ValueError):
            resolve_batch_triad(30, None, 2, 8)


class TestDeepSpeedConfig:
    def test_dict_config(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 16,
            "gradient_accumulation_steps": 1,
            "fp16": {"enabled": True, "initial_scale_power": 8},
            "zero_optimization": {"stage": 2, "overlap_comm": True},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        }, world_size=8)
        assert cfg.train_batch_size == 16
        assert cfg.train_micro_batch_size_per_gpu == 2
        assert cfg.fp16_enabled and not cfg.bfloat16_enabled
        assert cfg.fp16.initial_scale_power == 8
        assert cfg.zero_config.stage == 2
        assert cfg.optimizer.type == "AdamW"
        assert cfg.optimizer.params["lr"] == 1e-3

    def test_json_file(self, tmp_path):
        p = tmp_path / "ds_config.json"
        p.write_text(json.dumps({"train_micro_batch_size_per_gpu": 4, "bf16": {"enabled": True}}))
        cfg = DeepSpeedConfig(str(p), world_size=2)
        assert cfg.train_batch_size == 8
        assert cfg.bfloat16_enabled

    def test_base64_config(self):
        import base64

        blob = base64.urlsafe_b64encode(json.dumps({"train_batch_size": 4}).encode()).decode()
        cfg = DeepSpeedConfig(blob, world_size=4)
        assert cfg.train_batch_size == 4

    def test_auto_values(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 8,
            "gradient_clipping": "auto",
            "zero_optimization": {"stage": 3, "reduce_bucket_size": "auto",
                                   "stage3_prefetch_bucket_size": "auto"},
        }, world_size=8)
        assert cfg.gradient_clipping == 0.0
        assert cfg.zero_config.reduce_bucket_size == 500_000_000
        assert cfg.zero_config.was_auto("reduce_bucket_size")
        cfg.zero_config.fill_auto("reduce_bucket_size", 1024)
        assert cfg.zero_config.reduce_bucket_size == 1024

    def test_fp16_bf16_conflict(self):
        with pytest.raises(ValueError):
            DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True},
                             "bf16": {"enabled": True}}, world_size=8)

    def test_deprecated_cpu_offload(self):
        cfg = DeepSpeedConfig({"train_batch_size": 8,
                               "zero_optimization": {"stage": 2, "cpu_offload": True}}, world_size=8)
        assert cfg.zero_config.offload_optimizer.device == "cpu"

    def test_dotted_get(self):
        cfg = DeepSpeedConfig({"train_batch_size": 8, "zero_optimization": {"stage": 1}}, world_size=8)
        assert cfg.get("zero_optimization.stage") == 1
        assert cfg.get("zero_optimization.missing", "d") == "d"

    def test_mesh_section(self):
        cfg = DeepSpeedConfig({"train_batch_size": 8, "mesh": {"tp": 2, "fsdp": 4}}, world_size=8)
        assert cfg.mesh.tp == 2 and cfg.mesh.fsdp == 4

    def test_scheduler_optimizer_sections(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 8,
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_min_lr": 0, "warmup_max_lr": 1e-3,
                                     "warmup_num_steps": 100}},
        }, world_size=8)
        assert cfg.scheduler.type == "WarmupLR"
        assert cfg.scheduler.params["warmup_num_steps"] == 100
