"""KV host tier (serving/host_tier.py + the prefix cache's demote/promote
paths) and the intrusive-LRU eviction rewrite.

Contracts pinned here:
- ``HostPageStore`` bound + LRU eviction returns the overflowed keys;
- demote preserves the trie (interior nodes included) and promote
  re-homes byte-identically — greedy serving outputs are token-identical
  with the tier on/off at a pool size that previously evicted-to-drop,
  while the prefix hit ratio is STRICTLY higher with the tier on;
- store overflow invalidates exactly the trie paths that pointed at the
  dropped entries;
- the leak probe covers the {device, host} page partition after every
  scenario (pool partition exact AND trie/store/LRU-list bijections).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.serving import PagedKVPool, PrefixCache
from deepspeed_tpu.serving.host_tier import HostPageStore


@pytest.fixture(autouse=True)
def _no_unknown_finish_reasons():
    from deepspeed_tpu.monitor.metrics import get_registry

    yield
    c = get_registry().get("ds_serve_finished_total",
                           labels={"reason": "unknown"})
    assert c is None or c.value == 0


# ---------------------------------------------------------------------------
# HostPageStore units (no jax)
# ---------------------------------------------------------------------------

def test_host_store_bound_and_lru_overflow():
    store = HostPageStore(2)
    k1, ev = store.put({"k": np.ones(3)})
    assert ev == [] and len(store) == 1
    k2, ev = store.put({"k": np.full(3, 2.0)})
    assert ev == []
    assert store.touch(k1)                 # k1 now MRU -> k2 is LRU
    k3, ev = store.put({"k": np.full(3, 3.0)})
    assert ev == [k2] and len(store) == 2
    assert store.get(k2) is None and not store.touch(k2)
    assert (store.get(k1)["k"] == 1).all()
    store.drop(k3)
    assert len(store) == 1 and store.keys() == [k1]
    with pytest.raises(ValueError):
        HostPageStore(0)


# ---------------------------------------------------------------------------
# prefix-cache tier bookkeeping over a synthetic pool (no engine)
# ---------------------------------------------------------------------------

def _pages_payload(pages):
    """Synthetic per-page payloads keyed by page id so promote targets
    can be verified byte-for-byte."""
    return {p: {"k": np.full((2, 4), float(p))} for p in pages}


def _tiered_cache(pool, max_host=8):
    payloads = {}

    def fetch(page):
        return {"k": np.full((2, 4), float(page))}

    store = HostPageStore(max_host)
    cache = PrefixCache(pool, host_store=store, fetch_page=fetch)
    return cache, store, payloads


def test_demote_keeps_trie_matchable_and_promote_rehomes():
    pool = PagedKVPool(2, 64, page_tokens=4)
    cache, store, _ = _tiered_cache(pool)
    prompt = np.arange(1, 13, dtype=np.int32)          # 3 full pages
    assert pool.ensure(0, 12)
    pages = pool.owned(0)
    cache.insert(prompt, pages)
    pool.release(0)
    cache.check_no_leak()
    # demote ALL three (interior nodes included — structure preserved)
    freed_before = pool.pages_free
    for _ in range(3):
        assert cache.evict_lru() == 1
        cache.check_no_leak()
        pool.check_no_leak()
    assert pool.pages_free == freed_before + 3
    assert pool.pages_cached == 0 and len(store) == 3
    assert len(cache) == 3                              # trie intact
    # device-only legacy match sees nothing; node match sees all three
    assert cache.match(prompt) == []
    nodes = cache.match_nodes(prompt)
    assert len(nodes) == 3 and all(n.page < 0 for n in nodes)
    # promote the first chunk onto a fresh page
    dst = pool.alloc_page()
    payload = cache.host_payload(nodes[0])
    assert (payload["k"] == pages[0]).all()             # demoted bytes
    cache.promote(nodes[0], dst)
    cache.check_no_leak()
    assert nodes[0].page == dst and nodes[0].host_key is None
    assert len(store) == 2 and pool.pages_cached == 1
    assert cache.match(prompt) == [dst]                 # device again
    pool.check_no_leak()


def test_store_overflow_invalidates_trie_paths():
    pool = PagedKVPool(2, 64, page_tokens=4)
    cache, store, _ = _tiered_cache(pool, max_host=2)
    a = np.arange(1, 9, dtype=np.int32)                 # 2 pages
    b = np.arange(101, 109, dtype=np.int32)             # 2 pages
    for prompt in (a, b):
        assert pool.ensure(0, 8)
        cache.insert(prompt, pool.owned(0))
        pool.release(0)
    # demote a's two pages (LRU first), filling the 2-entry store
    cache.match_nodes(b)                                # b = MRU
    assert cache.evict_lru() == 1 and cache.evict_lru() == 1
    assert len(store) == 2 and len(cache) == 4
    # demoting b's pages overflows the store: a's entries drop and their
    # trie path is pruned
    assert cache.evict_lru() == 1
    cache.check_no_leak()
    pool.check_no_leak()
    assert len(cache) < 4
    assert cache.match_nodes(a) == [] or all(
        n.host_key is not None and store.touch(n.host_key)
        for n in cache.match_nodes(a))
    # everything still consistent after clearing
    cache.clear()
    assert len(store) == 0 and pool.pages_cached == 0
    pool.check_no_leak()
    cache.check_no_leak()


def test_intrusive_lru_eviction_order_drop_mode():
    """Tier off: the intrusive list must reproduce the PR 9 semantics —
    LRU leaf-first, live-referenced pages skipped in place."""
    pool = PagedKVPool(2, 64, page_tokens=4)
    cache = PrefixCache(pool)
    old = np.arange(100, 108, dtype=np.int32)
    new = np.arange(200, 208, dtype=np.int32)
    for prompt in (old, new):
        assert pool.ensure(0, 8)
        cache.insert(prompt, pool.owned(0))
        pool.release(0)
    new_pages = cache.match(new)
    _ = cache.match(old)
    _ = cache.match(new)                                # new = freshest
    assert cache.evict_lru() == 1                       # old's LEAF only
    assert len(cache.match(old)) == 1
    pool.adopt(1, new_pages)                            # protect 'new'
    evicted = 0
    while cache.evict_lru():
        evicted += 1
        pool.check_no_leak()
        cache.check_no_leak()
    assert evicted == 1                                 # old's root
    assert cache.match(new) == new_pages
    assert cache.match(old) == []
    pool.release(1)
    pool.check_no_leak()


def test_insert_upgrades_host_resident_chunk():
    """A request that re-computes a demoted chunk re-homes the node onto
    its freshly-computed device page (the host entry drops)."""
    pool = PagedKVPool(2, 64, page_tokens=4)
    cache, store, _ = _tiered_cache(pool)
    prompt = np.arange(1, 9, dtype=np.int32)
    assert pool.ensure(0, 8)
    first = pool.owned(0)
    cache.insert(prompt, first)
    pool.release(0)
    assert cache.evict_lru() == 1 and cache.evict_lru() == 1
    assert len(store) == 2
    # a new computation of the same prompt inserts device pages
    assert pool.ensure(1, 8)
    second = pool.owned(1)
    added = cache.insert(prompt, second)
    assert added == 2 and len(store) == 0
    assert cache.match(prompt) == second
    pool.release(1)
    cache.check_no_leak()
    pool.check_no_leak()


# ---------------------------------------------------------------------------
# end-to-end serving parity at a thrash-sized pool
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def setup(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    return model, params, ref


def _serve(model, params, **over):
    cfg = {"dtype": "float32", "max_out_tokens": 64, "kv_page_tokens": 16,
           **over}
    s = deepspeed_tpu.init_serving(model, config=cfg, num_slots=2,
                                   prefill_chunk=8, decode_block_tokens=3)
    s.set_params(params)
    return s


def _ref_out(ref, prompt, n):
    return np.asarray(ref.generate(np.asarray(prompt)[None],
                                   max_new_tokens=n,
                                   do_sample=False))[0, len(prompt):]


def test_host_tier_demote_promote_serving_parity(setup, rng):
    """THE acceptance scenario: a pool sized so cached history always
    evicts (previously: dropped), three distinct 2-page shared prefixes
    revisited across waves.  With the host tier on, outputs stay
    token-identical to generate() AND to the tier-off run, the hit ratio
    is STRICTLY higher, demote/promote counters move, and both leak
    probes hold after every wave."""
    model, params, ref = setup
    reg = get_registry()
    reg.enable()
    keys = jax.random.split(rng, 9)
    prefixes = [np.asarray(jax.random.randint(k, (32,), 0, 256))
                for k in keys[:3]]
    prompts = [np.concatenate(
        [prefixes[i % 3],
         np.asarray(jax.random.randint(k, (5 + i,), 0, 256))])
        for i, k in enumerate(keys[3:])]
    news = [6] * len(prompts)
    want = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
    res = {}
    try:
        for tier in (0, 16):
            reg.reset()
            # 96 pool tokens = 6 usable pages; 3 shared prefixes of 2
            # pages each + 2 live slots -> cached history always evicts
            serve = _serve(model, params, kv_pool_tokens=96,
                           kv_host_tier_pages=tier)
            assert (serve.host_store is not None) == bool(tier)
            outs = []
            for wave in range(2):
                for p, n in zip(prompts, news):
                    r = serve.submit(p, max_new_tokens=n)
                    serve.run()
                    outs.append(list(r.output_tokens))
                serve.scheduler.drain_finished()
                serve.pool.check_no_leak()
                serve.prefix_cache.check_no_leak()
            snap = reg.snapshot()
            hit = snap.get("ds_serve_prefix_hit_tokens_total", 0)
            miss = snap.get("ds_serve_prefix_miss_tokens_total", 0)
            res[tier] = {"outs": outs, "ratio": hit / max(hit + miss, 1),
                         "demote": snap.get("ds_serve_kv_demote_total", 0),
                         "promote": snap.get("ds_serve_kv_promote_total", 0)}
            serve.close()
    finally:
        reg.reset()
        reg.disable()
    expect = [list(w) for w in want] * 2
    for tier in (0, 16):
        assert res[tier]["outs"] == expect, \
            f"tier={tier} outputs diverged from generate()"
    assert res[16]["ratio"] > res[0]["ratio"], res
    assert res[16]["demote"] > 0 and res[16]["promote"] > 0
    assert res[0]["demote"] == 0 and res[0]["promote"] == 0


def test_host_tier_one_page_store_overflow_under_promotion(setup, rng):
    """Adversarial sizing (review finding): a ONE-page host store means
    any demote triggered by a promotion's own pool pressure pushes the
    promoting node's entry out of the store mid-admission.  The
    promotion must abort cleanly (no orphan pins, no adoption of
    freed pages — tombstoned nodes are skipped) and outputs stay
    token-identical through the chaos."""
    model, params, ref = setup
    keys = jax.random.split(rng, 8)
    prefixes = [np.asarray(jax.random.randint(k, (32,), 0, 256))
                for k in keys[:3]]
    prompts = [np.concatenate(
        [prefixes[i % 3],
         np.asarray(jax.random.randint(k, (4 + i,), 0, 256))])
        for i, k in enumerate(keys[3:])]
    want = [_ref_out(ref, p, 6) for p in prompts]
    serve = _serve(model, params, kv_pool_tokens=96,    # 6 usable pages
                   kv_host_tier_pages=1)
    try:
        for wave in range(3):
            for p, w in zip(prompts, want):
                r = serve.submit(p, max_new_tokens=6)
                serve.run()
                np.testing.assert_array_equal(
                    np.asarray(r.output_tokens), w,
                    err_msg=f"wave {wave} diverged under 1-page store "
                            f"overflow pressure")
                serve.pool.check_no_leak()
                serve.prefix_cache.check_no_leak()
            serve.scheduler.drain_finished()
        assert len(serve.host_store) <= 1
    finally:
        serve.close()


def test_host_tier_off_by_default(setup):
    model, params, _ = setup
    serve = _serve(model, params)
    try:
        assert serve.host_store is None
        assert serve.prefix_cache.host_store is None
    finally:
        serve.close()


def test_host_tier_preempt_resume_through_host(setup, rng):
    """A preempted request whose just-cached prompt pages were demoted
    under the very pressure that preempted it re-adopts them through the
    host tier on resume — token-identical continuation."""
    model, params, ref = setup
    serve = _serve(model, params, kv_pool_tokens=80,   # 5 usable pages
                   kv_host_tier_pages=16)
    try:
        k1, k2 = jax.random.split(rng)
        prompts = [np.asarray(jax.random.randint(k1, (18,), 0, 256)),
                   np.asarray(jax.random.randint(k2, (19,), 0, 256))]
        want = [_ref_out(ref, p, 30) for p in prompts]
        reqs = [serve.submit(p, max_new_tokens=30) for p in prompts]
        serve.run()
        assert sum(r.preemptions for r in reqs) >= 1
        for i, (req, w) in enumerate(zip(reqs, want)):
            np.testing.assert_array_equal(
                np.asarray(req.output_tokens), w,
                err_msg=f"request {i} diverged across preempt-resume "
                        f"through the host tier")
        victims = [r for r in reqs if r.preemptions]
        assert all(v.prefix_hit_tokens >= 16 for v in victims)
        serve.scheduler.drain_finished()
        serve.pool.check_no_leak()
        serve.prefix_cache.check_no_leak()
    finally:
        serve.close()
