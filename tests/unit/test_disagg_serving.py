"""Disaggregated prefill/decode serving (ISSUE 19) — THE tier-1
acceptance e2e plus the handoff/streaming pieces it is built from:

- wire codec: int8 blockwise page encoding round-trips, int8-cache
  planes ship verbatim (lossless), wire bytes < the dense twin;
- role-split fleet e2e: a 2-prefill + 2-decode fleet answers a
  shared-prefix trace through the router, every response token-identical
  to single-engine ``generate()``, KV pages moving int8 over
  ``/kv_offer`` + ``/kv_adopt``, zero leaked pages on both roles' pools
  after drain;
- token streaming: chunked ndjson events through replica front and
  router front, first chunk strictly before completion (TTFT < total),
  resume-from-token-N replays only the unsent suffix.

The mid-stream replica-kill chaos path lives in
tests/unit/test_serving_chaos.py (it rides ``make chaos`` too).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.metrics import MetricsRegistry
from deepspeed_tpu.serving import Router, RouterServer
from deepspeed_tpu.serving import handoff as hoff


# ---------------------------------------------------------------------------
# wire codec units (no model)
# ---------------------------------------------------------------------------

def test_handoff_page_codec_roundtrip_and_compression():
    """fp32/bf16 planes ride int8 blockwise (decode ~= original, wire <
    dense); int8 planes and *_scale planes ship RAW (byte-identical —
    the lossless path token identity rests on)."""
    rng = np.random.default_rng(0)
    payload = {
        "k": rng.standard_normal((2, 16, 4, 8)).astype(np.float32),
        "v": rng.standard_normal((2, 16, 4, 8)).astype(np.float32),
    }
    enc = hoff.encode_page(payload, wire="int8")
    dec = hoff.decode_page(enc)
    assert set(dec) == {"k", "v"}
    for name in ("k", "v"):
        a, b = payload[name], dec[name]
        assert b.shape == a.shape and b.dtype == a.dtype
        assert float(np.max(np.abs(a - b))) <= (
            np.max(np.abs(a)) / 127.0 + 1e-6)
    wire = hoff.wire_nbytes(enc)
    dense = hoff.dense_twin_nbytes(payload, 4)
    assert wire < dense, (wire, dense)

    qpayload = {
        "k": rng.integers(-127, 127, (2, 16, 4, 8)).astype(np.int8),
        "k_scale": rng.random((2, 16, 4, 1)).astype(np.float32),
    }
    enc = hoff.encode_page(qpayload, wire="int8")
    dec = hoff.decode_page(enc)
    np.testing.assert_array_equal(dec["k"], qpayload["k"])
    np.testing.assert_array_equal(dec["k_scale"], qpayload["k_scale"])


def test_handoff_raw_wire_is_lossless_for_any_dtype():
    rng = np.random.default_rng(1)
    payload = {"k": rng.standard_normal((1, 8, 2, 4)).astype(np.float32)}
    dec = hoff.decode_page(hoff.encode_page(payload, wire="raw"))
    np.testing.assert_array_equal(dec["k"], payload["k"])


def test_page_chunks_partitions_only_full_pages():
    toks = list(range(37))
    chunks = hoff.page_chunks(toks, 16)
    assert [len(c) for c in chunks] == [16, 16]
    assert list(chunks[0]) == list(range(16))


# ---------------------------------------------------------------------------
# the role-split fleet (module fixture: built once, several tests)
# ---------------------------------------------------------------------------

N_REQ = 10
SYS_LEN = 32


@pytest.fixture(scope="module")
def disagg_fleet(devices):
    """(ref, replicas{role: [engines]}, router, front, prompts, news,
    want): 2 prefill + 2 decode replicas behind the router front, a
    quantized KV cache on every engine (int8 cache planes -> the int8
    handoff is lossless -> outputs must be token-identical to the
    single-engine reference)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(7)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    cfg = {"dtype": "float32", "max_out_tokens": 96, "kv_page_tokens": 16,
           "quantize_kv_cache": True, "max_queue_depth": N_REQ + 2}
    np_rng = np.random.default_rng(3)
    shared = np_rng.integers(0, 256, size=SYS_LEN).astype(np.int32)
    prompts, news = [], []
    for i in range(N_REQ):
        tail = np_rng.integers(0, 256, size=int(
            np_rng.integers(3, 9))).astype(np.int32)
        if i % 4 != 3:                     # 3/4 share the system prompt
            prompts.append(np.concatenate([shared, tail]))
        else:
            prompts.append(np_rng.integers(
                0, 256, size=SYS_LEN // 2).astype(np.int32))
        news.append(int(np_rng.integers(8, 25)))
    ref = deepspeed_tpu.init_inference(model, config=dict(cfg))
    ref.set_params(params)
    want = [[int(t) for t in np.asarray(ref.generate(
                p[None], max_new_tokens=n, do_sample=False))[0, len(p):]]
            for p, n in zip(prompts, news)]
    replicas = {"prefill": [], "decode": []}
    for role in ("prefill", "prefill", "decode", "decode"):
        s = deepspeed_tpu.init_serving(
            model, config=dict(cfg), num_slots=2, prefill_chunk=16,
            decode_block_tokens=4, role=role, metrics_port=0,
            registry=MetricsRegistry().enable(), private_health=True,
            serve_loop=True)
        s.set_params(params)
        replicas[role].append(s)
    router = Router(
        [f"{r}{i}@{r}={s.metrics_server.url}"
         for r in ("prefill", "decode")
         for i, s in enumerate(replicas[r])],
        registry=MetricsRegistry().enable(), dispatch_rounds=8,
        retry_backoff=0.02, poll_interval=0.05, poll_timeout=1.0,
        request_timeout=120.0)
    router.refresh()
    front = RouterServer(router).start()
    yield ref, replicas, router, front, prompts, news, want
    front.stop()
    router.stop()
    for pool in replicas.values():
        for s in pool:
            s.close()


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def _stream(url, payload, timeout=120):
    """POST a streaming /generate; returns (tokens, first_chunk_s,
    total_s, final_event)."""
    t0 = time.perf_counter()
    req = urllib.request.Request(
        url + "/generate",
        data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"})
    toks, first, final = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        for line in resp:
            ev = json.loads(line)
            if ev.get("tokens"):
                if first is None:
                    first = time.perf_counter() - t0
                toks.extend(ev["tokens"])
            if ev.get("done") or ev.get("error"):
                final = ev
                break
    return toks, first, time.perf_counter() - t0, final


def test_disagg_fleet_e2e_token_identical_and_no_leaks(disagg_fleet):
    """THE acceptance e2e: the shared-prefix trace through the router —
    every request answered 200, token-identical to ``generate()``; the
    prefill phase really ran (handoff hops + int8 wire bytes < the dense
    twin); after drain both roles' pools and prefix caches hold zero
    leaked pages."""
    _ref, replicas, router, front, prompts, news, want = disagg_fleet
    results = [None] * N_REQ

    def client(i):
        payload = {"prompt": prompts[i].tolist(), "max_new_tokens": news[i],
                   "session": f"sess-{i % 3}", "timeout": 90}
        for _ in range(6):
            try:
                results[i] = _post(front.url, payload)
                if results[i][0] != 503:
                    return
            except urllib.error.HTTPError as exc:
                results[i] = (exc.code, {})
                if exc.code not in (429, 503):
                    return
            time.sleep(0.3)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_REQ)]
    for t in threads:
        t.start()
        time.sleep(0.02)
    for t in threads:
        t.join(timeout=180)
    for i, r in enumerate(results):
        assert r is not None and r[0] == 200, (i, r)
        assert r[1]["tokens"] == want[i], f"request {i} diverged"
    # the prefill pool did the prompt work and shipped pages int8
    shipped = wire = dense = 0
    for s in replicas["prefill"]:
        snap = s._registry.snapshot()
        shipped += int(snap.get("ds_serve_kv_handoff_pages_total", 0) or 0)
        fam = snap.get("ds_serve_kv_handoff_bytes_total") or {}
        wire += int(fam.get('{dtype="int8"}', 0) or 0)
        dense += int(fam.get('{dtype="dense"}', 0) or 0)
    assert shipped > 0, "no KV pages were handed off"
    assert 0 < wire < dense, (wire, dense)
    adopted = sum(int(s._registry.snapshot().get(
        "ds_serve_kv_adopted_pages_total", 0) or 0)
        for s in replicas["decode"])
    assert adopted > 0, "decode pool never adopted a handoff"
    assert router.registry.get(
        "ds_router_hops_total", labels={"kind": "handoff"}).value > 0
    # zero leaked pages on BOTH roles' pools after drain
    for pool in replicas.values():
        for s in pool:
            s.drain(timeout=60)
            assert s.scheduler.num_occupied == 0
            s.pool.check_no_leak()
            if s.prefix_cache is not None:
                s.prefix_cache.check_no_leak()
            s.resume_admission()


def test_disagg_streaming_ttft_before_completion(disagg_fleet):
    """Streaming through the ROUTER front on the role-split fleet: the
    token stream is identical to ``generate()`` and the first chunk
    lands strictly before the stream completes (TTFT < total latency —
    the user-visible point of streaming)."""
    _ref, _replicas, _router, front, prompts, news, want = disagg_fleet
    i = int(np.argmax(news))               # the longest generation
    toks, first, total, final = _stream(
        front.url, {"prompt": prompts[i].tolist(),
                    "max_new_tokens": news[i], "timeout": 90})
    assert final and final.get("done"), final
    assert toks == want[i]
    assert first is not None and first < total, (first, total)
    # more than one chunk actually arrived before the end (the stream
    # streamed, it didn't buffer-then-flush)
    assert final["n"] == len(toks)


def test_replica_stream_resume_from_token_n(disagg_fleet):
    """Resume-from-token-N at the replica: a second streaming dispatch
    carrying the same idempotency key and ``resume_from=k`` receives
    ONLY the unsent suffix (idempotent join — no second generation), so
    a router retry after a mid-stream socket death never replays sent
    tokens.  The decode replica serves both (its role accepts full
    generates)."""
    _ref, replicas, _router, front, prompts, news, want = disagg_fleet
    serve = replicas["decode"][0]
    url = serve.metrics_server.url
    reg = serve._registry
    base_sub = reg.get("ds_serve_submitted_total").value
    i = int(np.argmax(news))
    k = news[i] // 2
    payload = {"prompt": prompts[i].tolist(), "max_new_tokens": news[i],
               "idempotency_key": "stream-resume-pin", "timeout": 90}
    toks, _f, _t, final = _stream(url, payload)
    assert final.get("done") and toks == want[i]
    # replay with resume_from=k: only the suffix arrives, no new submit
    toks2, _f, _t, final2 = _stream(url, dict(payload, resume_from=k))
    assert final2.get("done")
    assert toks2 == want[i][k:]
    assert reg.get("ds_serve_submitted_total").value == base_sub + 1
    assert reg.get("ds_serve_idem_hits_total").value >= 1
    assert reg.get("ds_serve_stream_resumes_total").value >= 1


def test_monolithic_fallback_when_decode_pool_exhausted(disagg_fleet):
    """Degraded mode: with every prefill replica out of membership the
    router skips the prefill phase and the decode pool answers
    monolithically — same tokens, no 5xx."""
    _ref, replicas, router, front, prompts, news, want = disagg_fleet
    for rep in router.replicas:
        if rep.role == "prefill":
            rep.ready = False
    try:
        code, body = _post(front.url, {"prompt": prompts[0].tolist(),
                                       "max_new_tokens": news[0],
                                       "timeout": 90})
        assert code == 200 and body["tokens"] == want[0]
    finally:
        router.refresh()
        assert sum(r.ready for r in router.replicas) == 4
