"""Paged KV cache (serving/paged_kv.py + the paged serving engine):
allocator unit behavior (alloc/free/LIFO reuse, exhaustion, leak probe),
pool-pressure preempt-and-resume staying token-identical to sequential
``generate()``, the fixed-slot fallback layout, and the sync-free EOS
decode (finish events drained one block BEHIND dispatch — no per-step
host-device sync).  Engines are module-scoped where possible: compiles
dominate tier-1 wall time."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.serving import PagedKVPool


@pytest.fixture(autouse=True)
def _no_unknown_finish_reasons():
    """Same tier-1 guard as test_serving: every release path must
    attribute its finish reason."""
    from deepspeed_tpu.monitor.metrics import get_registry

    yield
    c = get_registry().get("ds_serve_finished_total",
                           labels={"reason": "unknown"})
    assert c is None or c.value == 0


# ---------------------------------------------------------------------------
# allocator unit tests (pure host bookkeeping, no jax)
# ---------------------------------------------------------------------------

def test_pool_alloc_free_reuse():
    pool = PagedKVPool(2, 64, page_tokens=16)
    assert pool.page == 16 and pool.slot_pages == 4 and pool.cache_len == 64
    assert pool.num_pages == 9                # 2 x 4 usable + junk page 0
    assert pool.ensure(0, 1) and pool.slot_pages_used(0) == 1
    assert pool.ensure(0, 16) and pool.slot_pages_used(0) == 1   # same page
    assert pool.ensure(0, 17) and pool.slot_pages_used(0) == 2   # crosses
    assert 0 not in pool.page_table[0, :2]    # junk page never allocated
    assert (pool.page_table[0, 2:] == 0).all()  # unallocated -> junk
    assert pool.ensure(1, 64)
    assert pool.pages_used == 6 and pool.pages_free == 2
    assert pool.ensure(0, 64) and pool.pages_free == 0
    with pytest.raises(ValueError):           # beyond the per-slot budget
        pool.ensure(0, 65)
    assert pool.release(1) == 4
    assert (pool.page_table[1] == 0).all() and pool.pages_free == 4
    lifo_next = pool._free[-1]                # most recently freed
    assert pool.ensure(1, 1) and pool.page_table[1, 0] == lifo_next
    pool.check_no_leak()


def test_pool_exhaustion_keeps_partial_grant():
    pool = PagedKVPool(2, 64, page_tokens=16, pool_tokens=80)  # 5 usable
    assert pool.ensure(0, 64)                 # 4 pages
    assert not pool.ensure(1, 32)             # needs 2, only 1 free
    assert pool.slot_pages_used(1) == 1       # the grant sticks
    pool.release(0)
    assert pool.ensure(1, 32)                 # satisfiable after release
    pool.check_no_leak()


def test_pool_sizing_defaults():
    pool = PagedKVPool(4, 300)
    # page = flash-decode block; window rounds 300 up to a page multiple
    assert pool.page == 256 and pool.slot_pages == 2
    assert pool.cache_len == 512
    assert pool.num_pages == 4 * 2 + 1
    assert PagedKVPool(4, 64).page == 64      # capped at pow2(max_out)
    # the pool never drops below one full slot window (no self-deadlock)
    assert PagedKVPool(4, 64, page_tokens=16,
                       pool_tokens=16).num_pages == 4 + 1


# ---------------------------------------------------------------------------
# end-to-end paged serving on the CPU mesh
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def setup(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    return model, params, ref


def _serve(model, params, **over):
    cfg = {"dtype": "float32", "max_out_tokens": 64, "kv_page_tokens": 16,
           **over}
    s = deepspeed_tpu.init_serving(model, config=cfg, num_slots=2,
                                   prefill_chunk=4, decode_block_tokens=3)
    s.set_params(params)
    return s


def _ref_out(ref, prompt, n):
    return np.asarray(ref.generate(np.asarray(prompt)[None],
                                   max_new_tokens=n,
                                   do_sample=False))[0, len(prompt):]


def test_pool_pressure_preempts_and_resumes_token_identical(setup, rng):
    """An oversubscribed pool (5 pages for two 3-page requests) must
    preempt the YOUNGEST slot, requeue it at the queue head, and resume it
    token-identically (the resume re-prefills prompt + produced tokens, so
    the greedy continuation cannot drift) — and no page may leak."""
    model, params, ref = setup
    serve = _serve(model, params, kv_pool_tokens=80)   # 5 usable pages
    assert serve.pool.num_pages == 6
    k1, k2 = jax.random.split(rng)
    prompts = [np.asarray(jax.random.randint(k1, (8,), 0, 256)),
               np.asarray(jax.random.randint(k2, (9,), 0, 256))]
    want = [_ref_out(ref, p, 40) for p in prompts]     # pos -> 47/48: 3 pages
    reqs = [serve.submit(p, max_new_tokens=40) for p in prompts]
    serve.run()
    assert sum(r.preemptions for r in reqs) >= 1, \
        "5-page pool serving two 3-page requests must preempt"
    for i, (req, w) in enumerate(zip(reqs, want)):
        np.testing.assert_array_equal(
            np.asarray(req.output_tokens), w,
            err_msg=f"request {i} diverged across the preempt-resume cycle")
    # free-on-finish freed everything; the allocator leaked nothing
    assert serve.pool.pages_used == 0
    serve.pool.check_no_leak()
    assert serve.scheduler.drain_finished()            # history drainable
    serve.pool.check_no_leak()


def test_preempt_correlates_flight_events_with_request_timelines(setup,
                                                                 rng):
    """ISSUE 7 correlation contract: with the flight recorder AND the
    request tracer on, a pool-pressure preempt-resume run must leave
    ``serve_admit`` / ``serve_preempt`` / ``serve_finish`` events whose
    ``rid`` fields match the tracer's completed timelines — a
    watchdog-tripped flight dump and ``/requestz`` exemplars join by id.
    The preempt event carries the reclaim size; the preempted request's
    timeline shows the ``preempted_wait`` phase."""
    from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.request_trace import get_request_tracer

    model, params, ref = setup
    flight = get_flight_recorder()
    tracer = get_request_tracer()
    reg = get_registry()
    serve = _serve(model, params, kv_pool_tokens=80)    # 5 usable pages
    flight.enable()
    flight.reset()      # the ring is process-global: drop any residue a
    reg.enable()        # previous test's enabled window left behind
    reg.reset()
    tracer.reset()
    tracer.enable()
    try:
        k1, k2 = jax.random.split(rng)
        prompts = [np.asarray(jax.random.randint(k1, (8,), 0, 256)),
                   np.asarray(jax.random.randint(k2, (9,), 0, 256))]
        reqs = [serve.submit(p, max_new_tokens=40) for p in prompts]
        serve.run()
        assert sum(r.preemptions for r in reqs) >= 1
        evs = flight.events()
        by_kind = {}
        for e in evs:
            by_kind.setdefault(e["kind"], []).append(e)
        rids = {r.request_id for r in reqs}
        # every lifecycle event names its request; ids line up with the
        # tracer's completed timelines
        assert {e["rid"] for e in by_kind["serve_finish"]} == rids
        assert {e["rid"] for e in by_kind["serve_admit"]} >= rids
        pre = by_kind["serve_preempt"]
        assert pre and all(e["rid"] in rids for e in pre)
        assert all(e["pages_freed"] > 0 and e["tokens_reclaimed"] > 0
                   for e in pre)
        timelines = {r["id"]: r for r in tracer.completed()}
        assert set(timelines) == rids
        for e in pre:
            rec = timelines[e["rid"]]
            assert rec["preemptions"] >= 1
            assert rec["phases"]["preempted_wait"] > 0
        for e in by_kind["serve_finish"]:
            assert timelines[e["rid"]]["reason"] == e["reason"]
        # queue wait is recorded once per REQUEST, not per admission: a
        # preempt's re-admission wait is the preempted_wait phase, never
        # a second (run-length-sized) queue_wait observation
        assert reg.get("ds_serve_queue_wait_seconds").count == len(reqs)
    finally:
        flight.disable()
        tracer.disable()
        reg.reset()


def test_eos_decode_runs_sync_free(setup, rng):
    """EOS workloads must not sync the host per decode block: every fetch
    of a block's (toks, valid) pair happens either at least one block
    BEHIND dispatch (the deferred drain — its RTT overlaps live device
    work) or after the host has nothing left to dispatch (tail flush).
    Instrumented at ``_fetch_block``, the single device->host readback
    point — the same style of structural assertion the no-EOS fast path's
    smoke test uses on ``_block``.  Outputs must equal the no-EOS greedy
    trajectory truncated at the first EOS occurrence (inclusive)."""
    model, params, ref = setup
    serve = _serve(model, params)                      # ample pool
    prompts = [np.asarray(jax.random.randint(k, (n,), 0, 256))
               for k, n in zip(jax.random.split(rng, 3), (3, 5, 7))]
    news = [8, 8, 8]
    base = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
    # request 0 stops mid-decode; request 1's eos never fires (drain
    # releases it by length); request 2 stops near the tail
    eos_ids = [int(base[0][3]),
               int((set(range(256)) - set(base[1].tolist())).pop()),
               int(base[2][-2])]

    def truncate(seq, eos):
        out = []
        for t in seq:
            out.append(int(t))
            if int(t) == eos:
                break
        return out

    want = [truncate(b, e) for b, e in zip(base, eos_ids)]
    fetches = []
    real_fetch = serve._fetch_block

    def probing(idx):
        fetches.append((idx, serve._next_block, bool(serve._active.any())))
        return real_fetch(idx)

    serve._fetch_block = probing
    try:
        reqs = [serve.submit(p, max_new_tokens=n, eos_token_id=e)
                for p, n, e in zip(prompts, news, eos_ids)]
        serve.run()
    finally:
        del serve.__dict__["_fetch_block"]
    assert fetches, "EOS workload must flow through the deferred drain"
    for idx, next_block, active in fetches:
        assert idx < next_block - 1 or not active, (
            f"block {idx} was fetched the same iteration it was dispatched "
            f"(next_block={next_block}) with rows still active — a "
            f"per-step host-device sync")
    for i, (req, w) in enumerate(zip(reqs, want)):
        assert req.output_tokens == w, (
            f"eos request {i}: {req.output_tokens} != {w}")
    assert reqs[0].finish_reason == "eos"
    assert reqs[1].finish_reason == "length"
    assert reqs[2].finish_reason == "eos"


def test_int8_kv_paged_parity(setup, rng):
    """Quantized KV + paged pool (the unfused gather path carries the
    int8 payloads AND their fp32 scales through the same page tables):
    token-identical to the int8-KV ``generate()``."""
    model, params, _ = setup
    cfg = {"dtype": "float32", "max_out_tokens": 64,
           "quantize_kv_cache": True, "kv_page_tokens": 16}
    ref = deepspeed_tpu.init_inference(model, config=cfg)
    ref.set_params(params)
    serve = deepspeed_tpu.init_serving(model, config=cfg, num_slots=2,
                                       prefill_chunk=4,
                                       decode_block_tokens=3)
    serve.set_params(params)
    assert serve.engine._dparams is None        # int8 KV = unfused path
    prompts = [np.asarray(jax.random.randint(k, (n,), 0, 256))
               for k, n in zip(jax.random.split(rng, 3), (3, 6, 9))]
    news = [5, 7, 4]
    want = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
    reqs = [serve.submit(p, max_new_tokens=n) for p, n in zip(prompts, news)]
    serve.run()
    for i, (req, w) in enumerate(zip(reqs, want)):
        np.testing.assert_array_equal(np.asarray(req.output_tokens), w,
                                      err_msg=f"int8-KV paged request {i}")


def test_fixed_slot_fallback_parity(setup, rng):
    """``paged_kv_cache=False`` keeps the PR 1 contiguous per-slot layout
    working (the reference the paged path is tested against)."""
    model, params, ref = setup
    serve = _serve(model, params, paged_kv_cache=False)
    assert serve.pool is None
    prompts = [np.asarray(jax.random.randint(k, (n,), 0, 256))
               for k, n in zip(jax.random.split(rng, 3), (3, 6, 11))]
    news = [5, 7, 4]
    want = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
    reqs = [serve.submit(p, max_new_tokens=n) for p, n in zip(prompts, news)]
    serve.run()
    for i, (req, w) in enumerate(zip(reqs, want)):
        np.testing.assert_array_equal(np.asarray(req.output_tokens), w,
                                      err_msg=f"fixed-slot request {i}")
