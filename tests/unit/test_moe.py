"""MoE tests (reference analog: tests/unit/moe/test_moe.py, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.moe import MoE, compute_capacity, moe_mlp, topk_gating


def test_topk_gating_properties(rng):
    N, E, k = 64, 8, 2
    gates = jax.nn.softmax(jax.random.normal(rng, (N, E)), axis=-1)
    C = compute_capacity(N, E, k, capacity_factor=1.25)
    combine, dispatch, aux = topk_gating(gates, k, C)
    assert combine.shape == (N, E, C)
    assert (np.asarray(dispatch.sum(axis=2)) <= 1).all()  # one slot per (token, expert)
    per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
    assert (per_expert <= C).all()  # capacity respected
    # kept tokens have combine weights normalized to ~1
    w = np.asarray(combine.sum(axis=(1, 2)))
    kept = np.asarray(dispatch.sum(axis=(1, 2))) == k  # tokens with all k slots kept
    np.testing.assert_allclose(w[kept], 1.0, rtol=1e-5)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_aux_loss_uniform_is_one(rng):
    # perfectly uniform routing -> aux loss == 1 (E * E * (1/E) * (1/E))
    N, E = 64, 8
    gates = jnp.full((N, E), 1.0 / E)
    # break argmax ties deterministically with tiny noise on distinct experts
    gates = gates + jax.nn.one_hot(jnp.arange(N) % E, E) * 1e-6
    _, _, aux = topk_gating(gates, 1, compute_capacity(N, E, 1, 2.0))
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-3)


def test_single_expert_equals_dense(rng):
    """E=1, k=1, ample capacity: MoE must reproduce the dense MLP exactly."""
    from types import SimpleNamespace
    B, S, D, F = 2, 16, 8, 32
    x = jax.random.normal(rng, (B, S, D))
    k1, k2, k3 = jax.random.split(rng, 3)
    w_up = jax.random.normal(k1, (1, D, F)) * 0.1
    w_gate = jax.random.normal(k2, (1, D, F)) * 0.1
    w_down = jax.random.normal(k3, (1, F, D)) * 0.1
    params = {"gate_w": jnp.zeros((D, 1)), "w_up": w_up, "w_gate": w_gate,
              "w_down": w_down}
    cfg = SimpleNamespace(num_experts=1, num_experts_per_tok=1,
                          moe_capacity_factor=1.0, activation="silu", glu=True)
    y, aux = moe_mlp(params, x, cfg, mesh=None)
    dense = (jax.nn.silu(x @ w_gate[0]) * (x @ w_up[0])) @ w_down[0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-4, atol=1e-5)


def test_moe_layer_api(rng):
    layer = MoE(hidden_size=16, num_experts=4, k=2, intermediate_size=32)
    params = layer.init(rng)
    x = jax.random.normal(rng, (2, 8, 16))
    y, aux = layer.apply(params, x)
    assert y.shape == x.shape
    assert np.isfinite(float(aux))


def test_mixtral_training_on_ep_mesh(devices, rng):
    """Mixtral-family model trains on an ep=4 mesh; loss decreases."""
    import deepspeed_tpu

    mesh = build_mesh(fsdp=2, ep=4, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("mixtral-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, num_experts=4)
    ds_config = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
                 "zero_optimization": {"stage": 1},
                 "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                 "steps_per_print": 1000}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config, mesh=mesh)
    toks = jax.random.randint(rng, (8, 64), 0, 256)
    losses = []
    for _ in range(5):
        loss = engine.forward((toks, toks))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_split_params_moe_vs_dense_mask(rng, devices):
    """Structural classification: only true MoE blocks (with a router) are
    masked as expert params; dense MLPs using the same leaf names are not."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    from deepspeed_tpu.moe import split_params_into_moe_groups

    toks = jnp.zeros((2, 32), jnp.int32)
    dense = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=128)
    mask = split_params_into_moe_groups(dense.init(rng, toks))
    assert not any(jax.tree.leaves(mask))  # dense model: nothing is expert

    moe = causal_lm("mixtral-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                    intermediate_size=128, num_heads=4, num_kv_heads=2,
                    vocab_size=128, num_experts=4)
    p = moe.init(rng, toks)
    m = split_params_into_moe_groups(p)
    assert m["layers"]["mlp"]["w_up"] and m["layers"]["mlp"]["w_down"]
    assert not m["layers"]["mlp"]["gate_w"]       # router is non-expert
    assert not m["layers"]["attn"]["wq"]


def test_top1_keeps_gate_gradient(rng):
    """k=1 combine weights must equal the raw gate prob (router gets task
    gradient), not be normalized to 1."""
    N, E = 32, 4
    gates = jax.nn.softmax(jax.random.normal(rng, (N, E)), axis=-1)
    combine, dispatch, _ = topk_gating(gates, 1, compute_capacity(N, E, 1, 2.0))
    w = np.asarray(combine.sum(axis=(1, 2)))
    kept = np.asarray(dispatch.sum(axis=(1, 2))) == 1
    top1 = np.asarray(gates.max(axis=-1))
    np.testing.assert_allclose(w[kept], top1[kept], rtol=1e-5)


def test_scatter_dispatch_matches_einsum(rng):
    """The O(N·k·D) scatter path must reproduce the GShard one-hot einsum
    path exactly (VERDICT r2 weak #9)."""
    from dataclasses import replace

    from deepspeed_tpu.models.config import ModelConfig
    from deepspeed_tpu.moe.sharded_moe import moe_mlp

    cfg = ModelConfig(num_experts=4, num_experts_per_tok=2, hidden_size=16,
                      intermediate_size=32, num_layers=1, num_heads=2,
                      vocab_size=64)
    x = jax.random.normal(rng, (2, 8, 16))
    params = {
        "gate_w": jax.random.normal(jax.random.fold_in(rng, 1), (16, 4)) * 0.1,
        "w_up": jax.random.normal(jax.random.fold_in(rng, 2), (4, 16, 32)) * 0.1,
        "w_gate": jax.random.normal(jax.random.fold_in(rng, 3), (4, 16, 32)) * 0.1,
        "w_down": jax.random.normal(jax.random.fold_in(rng, 4), (4, 32, 16)) * 0.1,
    }
    cfg.moe_dispatch = "scatter"
    y_s, aux_s = moe_mlp(params, x, cfg)
    cfg.moe_dispatch = "einsum"
    y_e, aux_e = moe_mlp(params, x, cfg)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)

    # gradients agree too (dispatch/combine both differentiable)
    def loss(p, mode):
        cfg.moe_dispatch = mode
        y, aux = moe_mlp(p, x, cfg)
        return (y.astype(jnp.float32) ** 2).sum() + aux

    gs = jax.grad(lambda p: loss(p, "scatter"))(params)
    ge = jax.grad(lambda p: loss(p, "einsum"))(params)
    for a, b in zip(jax.tree.leaves(gs), jax.tree.leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def _moe_fixture(rng, E=4, k=2, D=16, F=32, B=2, S=8):
    from deepspeed_tpu.models.config import ModelConfig

    cfg = ModelConfig(num_experts=E, num_experts_per_tok=k, hidden_size=D,
                      intermediate_size=F, num_layers=1, num_heads=2,
                      vocab_size=64)
    x = jax.random.normal(rng, (B, S, D))
    params = {
        "gate_w": jax.random.normal(jax.random.fold_in(rng, 1), (D, E)) * 0.1,
        "w_up": jax.random.normal(jax.random.fold_in(rng, 2), (E, D, F)) * 0.1,
        "w_gate": jax.random.normal(jax.random.fold_in(rng, 3), (E, D, F)) * 0.1,
        "w_down": jax.random.normal(jax.random.fold_in(rng, 4), (E, F, D)) * 0.1,
    }
    return cfg, x, params


def _dense_mixture(params, x, cfg):
    """Reference: every expert applied to every token, combined by the
    normalized top-k router weights — what no-drop MoE must equal."""
    B, S, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    xt = x.reshape(-1, D)
    gates = jax.nn.softmax(
        xt.astype(jnp.float32) @ params["gate_w"].astype(jnp.float32), -1)
    import numpy as _np

    act = jax.nn.silu
    up = jnp.einsum("nd,edf->enf", xt, params["w_up"])
    gate = jnp.einsum("nd,edf->enf", xt, params["w_gate"])
    per_e = jnp.einsum("enf,efd->end", act(gate) * up, params["w_down"])
    topv, topi = jax.lax.top_k(gates, k)
    w = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    sel = jnp.take_along_axis(per_e.transpose(1, 0, 2),
                              topi[:, :, None], axis=1)        # [N, k, D]
    y = (sel * w[..., None]).sum(1)
    return y.reshape(B, S, D)


@pytest.mark.parametrize("dispatch", ["scatter", "einsum"])
def test_no_drop_matches_dense_mixture(rng, dispatch):
    """drop_tokens=False (VERDICT r4 item 6): with capacity covering every
    token, the MoE output equals the dense top-k mixture exactly, even at a
    capacity factor that would otherwise drop most tokens."""
    from deepspeed_tpu.moe.sharded_moe import moe_mlp

    cfg, x, params = _moe_fixture(rng)
    cfg.moe_dispatch = dispatch
    cfg.moe_capacity_factor = 0.25        # would drop heavily if honored
    cfg.moe_drop_tokens = False
    y, aux = moe_mlp(params, x, cfg)
    want = _dense_mixture(params, x, cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    # and with dropping at that factor the outputs must NOT match (the
    # no-drop path is doing real work)
    cfg.moe_drop_tokens = True
    y_drop, _ = moe_mlp(params, x, cfg)
    assert np.abs(np.asarray(y_drop) - np.asarray(want)).max() > 1e-3


def test_rts_noop_when_capacity_ample(rng):
    """Random token selection reorders only the capacity contest: with room
    for every token the result is identical to sequential selection."""
    from deepspeed_tpu.moe.sharded_moe import moe_mlp

    cfg, x, params = _moe_fixture(rng)
    cfg.moe_capacity_factor = 100.0
    y0, aux0 = moe_mlp(params, x, cfg)
    cfg.moe_use_rts = True
    y1, aux1 = moe_mlp(params, x, cfg, rng=jax.random.PRNGKey(5))
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux0), float(aux1), rtol=1e-6)


def test_rts_randomizes_overflow_victims(rng):
    """Under a tight capacity, sequence order decides the dropped tokens;
    RTS decides randomly — different keys must drop different tokens, and
    late-sequence tokens must stop being the systematic victims."""
    from deepspeed_tpu.moe.sharded_moe import moe_mlp

    cfg, x, params = _moe_fixture(rng, B=1, S=32)
    cfg.moe_capacity_factor = 0.25
    cfg.moe_use_rts = True

    def kept_mask(key):
        from deepspeed_tpu.moe.sharded_moe import (compute_capacity,
                                                   topk_assignments)
        xt = x.reshape(-1, cfg.hidden_size)
        gates = jax.nn.softmax(
            xt.astype(jnp.float32) @ params["gate_w"].astype(jnp.float32), -1)
        C = compute_capacity(xt.shape[0], cfg.num_experts,
                             cfg.num_experts_per_tok,
                             cfg.moe_capacity_factor)
        _, pos, w, _ = topk_assignments(gates, cfg.num_experts_per_tok, C,
                                        key, True)
        return np.asarray((w > 0).any(-1))

    m1, m2 = kept_mask(jax.random.PRNGKey(0)), kept_mask(jax.random.PRNGKey(9))
    assert m1.shape == (32,)
    assert not np.array_equal(m1, m2), "different keys must change victims"
    # model-level: rng=None still works (content-derived key)
    y, _ = moe_mlp(params, x, cfg)
    assert np.isfinite(np.asarray(y)).all()
