"""Quantized/compressed collective tests (VERDICT r2 item 6).

Correctness vs dense equivalents on the 8-device mesh + comm-volume
accounting through CommsLogger.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import comm as comm_api
from deepspeed_tpu.comm.mesh import build_mesh
from deepspeed_tpu.runtime.comm.quantized import (block_dequantize, block_quantize,
                                                  compressed_allreduce, pack_signs,
                                                  quantized_all_gather,
                                                  quantized_reduce_scatter,
                                                  unpack_signs)


@pytest.fixture()
def dp_mesh(devices):
    return build_mesh(dp=8, devices=devices)


def test_block_quantize_roundtrip(rng):
    x = jax.random.normal(rng, (1000,)) * 3.0
    q, s, pad = block_quantize(x, block=256)
    out = block_dequantize(q, s, pad, x.shape)
    assert np.abs(np.asarray(out - x)).max() <= np.abs(np.asarray(x)).max() / 127 + 1e-6


def test_sign_pack_roundtrip(rng):
    x = jax.random.normal(rng, (77,))
    packed = pack_signs(x)
    assert packed.dtype == jnp.uint8 and packed.size == 10  # ceil(77/8)
    signs = unpack_signs(packed, 77)
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_quantized_all_gather_matches_dense(dp_mesh, rng):
    x = jax.random.normal(rng, (16, 32))

    def body(xl):
        return quantized_all_gather(xl, "dp")

    out = jax.jit(jax.shard_map(body, mesh=dp_mesh, in_specs=P("dp"),
                                out_specs=P("dp"), check_vma=False))(x)
    # each rank's gathered copy equals the full tensor within quant error
    np.testing.assert_allclose(np.asarray(out[:16]), np.asarray(x),
                               atol=float(jnp.abs(x).max()) / 127 + 1e-6)


def test_quantized_reduce_scatter_matches_dense(dp_mesh, rng):
    x = jax.random.normal(rng, (8, 64))  # per-rank contribution

    def body(xl):
        # xl: [1, 64] local slice; build a full local tensor so every rank
        # contributes to every shard
        full = jnp.tile(xl, (8, 1))
        return quantized_reduce_scatter(full, "dp")

    out = jax.jit(jax.shard_map(body, mesh=dp_mesh, in_specs=P("dp"),
                                out_specs=P("dp"), check_vma=False))(x)
    want = np.asarray(x).sum(axis=0)  # every shard = sum over ranks
    got = np.asarray(out)
    for r in range(8):
        np.testing.assert_allclose(got[r], want, atol=8 * 0.05 + np.abs(want).max() / 30,
                                   rtol=0.1)


def test_compressed_allreduce_error_feedback_converges(dp_mesh, rng):
    """Error feedback makes repeated compressed allreduce track the dense
    mean: accumulated output over steps approaches accumulated dense mean."""
    xs = jax.random.normal(rng, (8, 128))
    dense_mean = np.asarray(xs).mean(axis=0)

    def body(xl):
        x = xl[0]
        err = jnp.zeros_like(x)
        serr = jnp.zeros((x.size // 8,), jnp.float32)

        def step(carry, _):
            err, serr, acc = carry
            out, err, serr = compressed_allreduce(x, err, serr, "dp")
            return (err, serr, acc + out), None

        (_, _, acc), _ = jax.lax.scan(step, (err, serr, jnp.zeros_like(x)),
                                      None, length=12)
        return (acc / 12)[None]

    out = jax.jit(jax.shard_map(body, mesh=dp_mesh, in_specs=P("dp"),
                                out_specs=P("dp"), check_vma=False))(xs)
    got = np.asarray(out[0])
    # the time-average converges to the dense mean (EF property)
    assert np.abs(got - dense_mean).mean() < 0.15 * np.abs(dense_mean).mean() + 0.05


def test_comm_volume_reduction(dp_mesh, rng):
    """Compressed payload bytes must be ~1/4 of the bf16 dense volume."""
    comm_api.comms_logger.configure(enabled=True)
    comm_api.comms_logger.reset()
    x = jax.random.normal(rng, (8, 4096))

    def body(xl):
        x = xl[0]
        err = jnp.zeros_like(x)
        serr = jnp.zeros((x.size // 8,), jnp.float32)
        out, _, _ = compressed_allreduce(x, err, serr, "dp")
        return out[None]

    jax.jit(jax.shard_map(body, mesh=dp_mesh, in_specs=P("dp"),
                          out_specs=P("dp"), check_vma=False))(x)
    recs = comm_api.comms_logger.bytes
    comp_bytes = sum(v for k, v in recs.items() if "compressed" in k)
    dense_bytes = 4096 * 2  # one bf16 allreduce payload per rank
    assert 0 < comp_bytes < dense_bytes / 4, (comp_bytes, dense_bytes)
    comm_api.comms_logger.configure(enabled=False)
    comm_api.comms_logger.reset()
