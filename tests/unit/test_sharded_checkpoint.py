"""Sharded checkpoint engine tests (VERDICT r2 item 2).

Every process writes only its addressable shards; loads reshard to any
target sharding; peak host memory stays O(shard), not O(model).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.runtime.checkpoint_engine import (ShardedCheckpointEngine,
                                                     is_sharded_checkpoint)
from deepspeed_tpu.runtime.checkpoint_engine.sharded import nest_keystrs
from tests.unit.simple_model import SimpleModel, random_dataset


def test_roundtrip_resharded(tmp_path, mesh8):
    """Save under fsdp sharding, load replicated AND load fsdp-sharded."""
    eng = ShardedCheckpointEngine()
    sh = NamedSharding(mesh8, P("fsdp"))
    rep = NamedSharding(mesh8, P())
    tree = {"w": jax.device_put(jnp.arange(64.0).reshape(16, 4), sh),
            "b": jax.device_put(jnp.arange(8.0), sh),
            "step": jnp.asarray(7, jnp.int32)}
    path = str(tmp_path / "ckpt")
    eng.save(tree, path)
    assert is_sharded_checkpoint(path)

    # replicated load
    out = eng.load(path, shardings={"w": rep, "b": rep, "step": rep})
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(tree["w"]))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.asarray(tree["b"]))
    assert int(out["step"]) == 7

    # sharded load on a different axis layout
    sh2 = NamedSharding(mesh8, P(None, None))
    out2 = eng.load(path, shardings={"w": NamedSharding(mesh8, P("fsdp", None)),
                                     "b": sh2.with_spec(P(None)) if hasattr(sh2, "with_spec")
                                     else NamedSharding(mesh8, P(None)),
                                     "step": rep})
    np.testing.assert_array_equal(np.asarray(out2["w"]), np.asarray(tree["w"]))


def test_streaming_peak_host_bytes(tmp_path, mesh8):
    """Peak host buffer during save must be one shard, not the whole model."""
    eng = ShardedCheckpointEngine()
    sh = NamedSharding(mesh8, P("fsdp"))
    big = jax.device_put(jnp.zeros((1024, 128), jnp.float32), sh)  # 512 KiB
    eng.save({"big": big}, str(tmp_path / "c"))
    model_bytes = big.size * big.dtype.itemsize
    assert eng.max_bytes_in_flight <= model_bytes // 8 + 1024, \
        (eng.max_bytes_in_flight, model_bytes)


def test_flat_dict_load_and_nest(tmp_path, mesh8):
    eng = ShardedCheckpointEngine()
    tree = {"a": {"b": jnp.ones((4, 4)), "c": jnp.zeros((2,))}}
    eng.save(tree, str(tmp_path / "c"))
    flat = eng.load(str(tmp_path / "c"))
    nested = nest_keystrs(flat)
    np.testing.assert_array_equal(nested["a"]["b"], np.ones((4, 4)))
    np.testing.assert_array_equal(nested["a"]["c"], np.zeros((2,)))


def test_bf16_dtype_roundtrip(tmp_path, mesh8):
    eng = ShardedCheckpointEngine()
    tree = {"w": jnp.full((8, 8), 1.5, jnp.bfloat16)}
    eng.save(tree, str(tmp_path / "c"))
    out = eng.load(str(tmp_path / "c"))
    arr = out["['w']"]
    assert str(arr.dtype) == "bfloat16"
    np.testing.assert_array_equal(np.asarray(arr, np.float32), 1.5)


def test_missing_leaf_raises(tmp_path, mesh8):
    eng = ShardedCheckpointEngine()
    eng.save({"w": jnp.ones((2,))}, str(tmp_path / "c"))
    rep = NamedSharding(mesh8, P())
    with pytest.raises(KeyError):
        eng.load(str(tmp_path / "c"), shardings={"nope": rep})


def _make_engine(stage, tmp=None):
    cfg = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": stage}}
    x, y = random_dataset(n=16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, rng=jax.random.PRNGKey(3))
    return engine, (x, y)


def test_engine_checkpoint_no_full_gather(tmp_path):
    """Engine save writes the sharded layout and never gathers the model;
    a zero-3 save loads back into a zero-0 engine (cross-stage reshard)."""
    engine, (x, y) = _make_engine(stage=3)
    engine.forward((x[:8], y[:8]))
    engine.step()
    ckpt = engine.save_checkpoint(str(tmp_path), tag="t1")
    assert is_sharded_checkpoint(os.path.join(ckpt, "model_states"))
    assert is_sharded_checkpoint(os.path.join(ckpt, "optim_states"))
    # peak host buffer bounded by largest shard (params sharded over fsdp=8)
    n_bytes = sum(l.size * l.dtype.itemsize
                  for l in jax.tree.leaves(engine.state.params))
    assert engine.checkpoint_engine.max_bytes_in_flight < n_bytes, \
        "save should stream shards, not materialize the model"
    saved = jax.device_get(engine.state.params)

    other, _ = _make_engine(stage=0)
    other.forward((x[:8], y[:8]))
    other.step()
    other.load_checkpoint(str(tmp_path), tag="t1")
    for a, b in zip(jax.tree.leaves(saved), jax.tree.leaves(jax.device_get(other.state.params))):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=1e-6)


def test_save_16bit_model_sharded(tmp_path):
    engine, (x, y) = _make_engine(stage=1)
    engine.forward((x[:8], y[:8]))
    engine.step()
    out = engine.save_16bit_model(str(tmp_path))
    assert is_sharded_checkpoint(out)
    eng = ShardedCheckpointEngine()
    flat = eng.load(out)
    assert len(flat) == len(jax.tree.leaves(engine.state.params))


def test_reshard_across_mesh_shapes(tmp_path, devices):
    """Save on an fsdp=8 mesh, resume on a dp=2 x fsdp=4 mesh (different
    axis factorization): each device reads only the byte ranges backing its
    new slice."""
    from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
    from deepspeed_tpu.models import causal_lm

    kw = dict(num_layers=2, hidden_size=64, intermediate_size=128,
              num_heads=4, num_kv_heads=2, vocab_size=256, remat=False)
    cfg = {"train_batch_size": 8, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3}, "steps_per_print": 10**9}
    toks = jax.random.randint(jax.random.PRNGKey(0), (8, 32), 0, 256)

    mesh_a = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh_a)
    model_a = causal_lm("llama-tiny", mesh=mesh_a, **kw)
    ea, _, _, _ = deepspeed_tpu.initialize(model=model_a, config=cfg,
                                           mesh=mesh_a, rng=jax.random.PRNGKey(1))
    ea.forward((toks, toks))
    ea.step()
    ea.save_checkpoint(str(tmp_path), tag="x")
    saved = jax.device_get(ea.state.params)

    mesh_b = build_mesh(dp=2, fsdp=4, devices=devices)
    set_global_mesh(mesh_b)
    model_b = causal_lm("llama-tiny", mesh=mesh_b, **kw)
    eb, _, _, _ = deepspeed_tpu.initialize(model=model_b, config=cfg,
                                           mesh=mesh_b, rng=jax.random.PRNGKey(2))
    eb.forward((toks, toks))
    eb.step()
    eb.load_checkpoint(str(tmp_path), tag="x")
    for a, b in zip(jax.tree.leaves(saved),
                    jax.tree.leaves(jax.device_get(eb.state.params))):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    # training continues on the new topology
    loss = eb.forward((toks, toks))
    eb.step()
    assert np.isfinite(float(loss))
