"""Continuous-profiler tests (ISSUE 20 tentpole).

The offline half (history ring, window schema, differ, duty-cycle
scheduler) is exercised with synthetic summaries and a fake clock; the
live half runs real scheduled TraceCaptures against CPU training AND
serving engines at a forced cadence and checks the acceptance contract:
>=2 persisted windows, per-scope device-seconds bounded by the window
wall, telescoping capture wall, and the registry/flight commits — all
with no operator ``/profilez`` anywhere.  The disabled default must keep
the compiled step program byte-identical and allocate nothing.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import pytest

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry
from deepspeed_tpu.profiling import continuous
from deepspeed_tpu.profiling.device_trace import perfetto_supported
from tests.unit.simple_model import SimpleModel, random_dataset

needs_perfetto = pytest.mark.skipif(
    not perfetto_supported(),
    reason="this jax's start_trace has no create_perfetto_trace")

PHASES = ("fwd_bwd", "optimizer", "comm", "other", "gap")


def _summary(fwd=0.010, opt=0.002, comm=0.001, other=0.0005, gap=0.0005,
             steps=2, lo=100.0, ag=None):
    """Synthetic ``summarize_trace`` result: per-step phase seconds that
    partition the per-step wall, one all_gather device collective."""
    per_step_wall = fwd + opt + comm + other + gap
    window = per_step_wall * steps
    per = {"fwd_bwd_s": fwd, "optimizer_s": opt, "comm_s": comm,
           "other_s": other, "gap_s": gap}
    return {"steps": steps, "window_s": window,
            "device_busy_s": window - gap * steps,
            "phases": {k: v * steps for k, v in per.items()},
            "per_step": per,
            "comm_device": {"all_gather": {
                "seconds": (comm if ag is None else ag) * steps,
                "count": 2 * steps}},
            "clock": {"anchor_unix": lo, "window_unix_lo": lo,
                      "window_unix_hi": lo + window},
            "degraded": False, "source": "synthetic"}


def _window(tmp=None, seq=None, **kw):
    w = continuous.build_window(_summary(**kw), engine="train",
                                step=10, capture_wall_s=0.05,
                                coverage_ratio=0.01, overhead_ratio=0.02)
    if seq is not None:
        w["seq"] = seq
    return w


# ---------------------------------------------------------------------------
# history ring
# ---------------------------------------------------------------------------


def test_history_ring_roundtrip_seq_and_atomicity(tmp_path):
    ring = continuous.HistoryRing(str(tmp_path / "hist"))
    assert ring.paths() == [] and ring.latest(3) == []
    p1 = ring.append(_window())
    p2 = ring.append(_window())
    assert [os.path.basename(p) for p in ring.paths()] == \
        ["ds_prof_window_00000001.json", "ds_prof_window_00000002.json"]
    assert (p1, p2) == tuple(ring.paths())
    # atomic writes: no .tmp litter ever visible
    assert not [n for n in os.listdir(ring.directory) if n.endswith(".tmp")]
    wins = ring.latest(5)
    assert [w["seq"] for w in wins] == [1, 2]   # oldest-first
    # a torn file (crashed writer) loads as None and is skipped
    with open(ring.paths()[0], "w") as fh:
        fh.write('{"seq": 1, "scopes": {')
    assert continuous.HistoryRing.load(ring.paths()[0]) is None
    assert [w["seq"] for w in ring.latest(5)] == [2]


def test_history_ring_retention_by_count_and_bytes(tmp_path):
    ring = continuous.HistoryRing(str(tmp_path), max_windows=3)
    for _ in range(5):
        ring.append(_window())
    assert [w["seq"] for w in ring.latest(9)] == [3, 4, 5]
    # bytes cap: every file is several hundred bytes, so a 1KB budget
    # keeps at most a couple of windows regardless of max_windows
    ring_b = continuous.HistoryRing(str(tmp_path / "b"), max_windows=99,
                                    max_bytes=1024)
    for _ in range(6):
        ring_b.append(_window())
    paths = ring_b.paths()
    assert len(paths) < 6
    assert sum(os.path.getsize(p) for p in paths) <= 1024
    # the NEWEST window survives pruning
    assert ring_b.latest(1)[0]["seq"] == 6


# ---------------------------------------------------------------------------
# window schema + differ
# ---------------------------------------------------------------------------


def test_build_window_scopes_partition_per_step_wall():
    w = _window()
    per_step_wall = w["window_s"] / w["steps"]
    assert sum(w["scopes"][p] for p in PHASES) == \
        pytest.approx(per_step_wall)
    # device collectives ride as per-step comm_<op> lanes
    assert w["scopes"]["comm_all_gather"] == pytest.approx(0.001)
    assert w["busy_ratio"] < 1.0 and w["clock"]["window_unix_lo"] == 100.0


def test_diff_windows_flags_seeded_comm_regression():
    prev = _window()
    # 8x per-step comm: the lane itself AND the per-step wall (0.014 ->
    # 0.021, +50%) both clear the 25% default tolerance
    cur = _window(comm=0.008)
    regs = continuous.diff_windows(prev, cur)
    names = [r["scope"] for r in regs]
    assert "comm" in names and "comm_all_gather" in names
    # the slowdown also moves the synthesized per-step wall lane
    assert "step_time" in names
    top = regs[0]
    assert top["cur_s"] > top["prev_s"]
    assert top["rel"] > top["tol"]
    # clean twin: byte-equal scopes produce no findings
    assert continuous.diff_windows(prev, _window()) == []


def test_diff_windows_tolerance_rules_and_noise_floor():
    # gap is a noisy remainder lane: default bar is 50%, so +40% passes
    prev = _window(gap=0.0010)
    cur = _window(gap=0.0014)
    assert continuous.diff_windows(prev, cur) == []
    assert [r["scope"] for r in
            continuous.diff_windows(prev, _window(gap=0.0016))] == ["gap"]
    # shared-substring override (the perf_ledger contract: first wins)
    assert continuous.tolerance_for("comm_all_gather",
                                    [("all_gather", 0.9)]) == 0.9
    assert continuous.tolerance_for("gap") == 0.50
    assert continuous.tolerance_for("fwd_bwd") == continuous.DEFAULT_TOLERANCE
    # sub-floor lanes never alert (5e-5s default): a 10x move on a
    # nanoseconds-scale scope is measurement noise
    prev = _window(other=1e-6)
    assert not [r for r in continuous.diff_windows(prev, _window(other=1e-5))
                if r["scope"] == "other"]


# ---------------------------------------------------------------------------
# scheduler: cadence + duty cycle (fake clock, no real captures)
# ---------------------------------------------------------------------------


def test_due_every_n_steps_or_t_seconds(tmp_path):
    t = [0.0]
    prof = continuous.ContinuousProfiler(
        engine="sched-test", every_steps=10, every_seconds=5.0,
        history_dir=str(tmp_path), clock=lambda: t[0])
    try:
        assert not prof.due(5)
        assert prof.due(10)          # step cadence
        t[0] = 6.0
        assert prof.due(1)           # time cadence fires first
    finally:
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("sched-test", None)


def test_duty_cycle_defers_and_counts(tmp_path):
    t = [100.0]
    prof = continuous.ContinuousProfiler(
        engine="duty-test", every_steps=1, max_duty_cycle=0.01,
        history_dir=str(tmp_path), clock=lambda: t[0])
    try:
        assert prof._duty_ok()       # first window: nothing measured yet
        # book one expensive window: 1s of overhead over 10s of run is a
        # 10% duty cycle — 10x over the 1% cap
        prof.windows = 1
        prof._overhead_s = 1.0
        t[0] = 110.0
        assert prof.due(50)
        assert not prof.maybe_begin(50)      # deferred BEFORE any capture
        assert prof.skipped_duty == 1
        assert prof._last_t == 110.0         # timer cadence pushed back
        # budget recovers as wall clock accrues: 1s + 1s est over 300s
        t[0] = 400.0
        assert prof._duty_ok()
    finally:
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("duty-test", None)


# ---------------------------------------------------------------------------
# regression publish: registry counter + flight event
# ---------------------------------------------------------------------------


class _FakeFlight:
    def __init__(self):
        self.events = []

    def record(self, kind, **fields):
        self.events.append((kind, fields))


def test_publish_commits_gauges_counters_and_flight(tmp_path):
    reg = MetricsRegistry().enable()
    continuous.ensure_registered(reg)
    flight = _FakeFlight()
    prof = continuous.ContinuousProfiler(
        engine="pub-test", history_dir=str(tmp_path), registry=reg,
        flight=flight)
    try:
        prev, cur = _window(), _window(comm=0.004)
        regs = continuous.diff_windows(prev, cur)
        prof._publish(cur, regs)
        snap = json.loads(reg.statz_json())["metrics"]
        assert snap["ds_prof_window_seconds"] == \
            pytest.approx(cur["window_s"])
        assert snap["ds_prof_windows_total"] == 1
        assert '{scope="fwd_bwd"}' in snap["ds_prof_scope_device_seconds"]
        assert {'{scope="comm"}', '{scope="comm_all_gather"}'} <= \
            set(snap["ds_prof_regressions_total"])
        kinds = [k for k, _ in flight.events]
        assert "prof_regression" in kinds
        ev = dict(flight.events)[("prof_regression")]
        assert ev["engine"] == "pub-test" and ev["rel"] > ev["tol"]
    finally:
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("pub-test", None)


# ---------------------------------------------------------------------------
# disabled default: one branch, zero allocation, identical programs
# ---------------------------------------------------------------------------


def _train_cfg(extra=None):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 10**9}
    cfg.update(extra or {})
    return cfg


def test_disabled_default_off_contract(tmp_path):
    x, y = random_dataset(n=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8), config=_train_cfg(),
        rng=jax.random.PRNGKey(0))
    assert engine._cprof is None
    before = set(get_registry().snapshot())
    for _ in range(2):
        loss = engine.forward((x, y))
        engine.backward(loss)
        engine.step()
    # zero captures, zero new ds_prof series, no history dir anywhere
    new = {k for k in set(get_registry().snapshot()) - before
           if k.startswith("ds_prof_")}
    assert new == set()
    # the compiled step program is byte-identical to an armed-but-idle
    # engine's: the profiler lives entirely OUTSIDE the jit boundary
    hist = str(tmp_path / "hist")
    armed, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8),
        config=_train_cfg({"continuous_profiler": {
            "enabled": True, "every_steps": 10**6,
            "every_seconds": 10**6, "history_dir": hist}}),
        rng=jax.random.PRNGKey(0))
    assert armed._cprof is not None and not armed._cprof.active
    loss = armed.forward((x, y))
    armed.backward(loss)
    armed.step()
    rng = jax.random.PRNGKey(1)
    txt_off = engine._accum_fn.lower(
        engine.state, (x, y), rng).compile().as_text()
    txt_on = armed._accum_fn.lower(
        armed.state, (x, y), rng).compile().as_text()
    assert txt_off == txt_on
    with continuous._ACTIVE_LOCK:
        continuous._ACTIVE.pop("train", None)


# ---------------------------------------------------------------------------
# live e2e: scheduled windows from real CPU training / serving loops
# ---------------------------------------------------------------------------


def _assert_window_contract(w, engine):
    assert w["engine"] == engine and w["schema_version"] == 1
    per_step_wall = w["window_s"] / max(1, w["steps"])
    phase_s = sum(w["scopes"].get(p, 0.0) for p in PHASES)
    # the five phase lanes partition the per-step wall (float slack)
    assert phase_s <= per_step_wall * 1.001
    assert 0.0 < w["coverage_ratio"] <= 1.0
    assert w["coverage_ratio"] <= w["overhead_ratio"] <= 1.0


@needs_perfetto
def test_training_engine_produces_scheduled_windows(tmp_path):
    """A stepping CPU engine with the profiler armed at forced cadence
    commits >=2 history windows with NOBODY calling /profilez."""
    hist = str(tmp_path / "hist")
    x, y = random_dataset(n=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=8),
        config=_train_cfg({"continuous_profiler": {
            "enabled": True, "every_steps": 2, "every_seconds": 3600.0,
            "capture_steps": 1, "max_duty_cycle": 1.0,
            "history_dir": hist}}),
        rng=jax.random.PRNGKey(0))
    try:
        assert engine._cprof is not None
        ring = engine._cprof.ring
        n = 0
        import time as _time
        t0 = _time.perf_counter()
        while n < 16 and len(ring.paths()) < 2:
            loss = engine.forward((x, y))
            engine.backward(loss)
            engine.step()
            n += 1
        wall = _time.perf_counter() - t0
        wins = ring.latest(4)
        assert len(wins) >= 2, f"{len(wins)} windows after {n} steps"
        for w in wins:
            _assert_window_contract(w, "train")
        # telescoping: capture wall summed over windows fits the run wall
        assert sum(w["capture_wall_s"] for w in wins) <= wall
        assert wins[-1]["trigger"] == "continuous"
        snap = get_registry().snapshot()
        assert snap.get("ds_prof_windows_total", 0) >= 2
        assert snap.get("ds_prof_window_seconds", 0) > 0
        # in-flight capture dir is cleaned up after each decompose
        assert not os.path.exists(os.path.join(hist, "_capture"))
    finally:
        if engine._cprof is not None:
            engine._cprof.close()
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("train", None)


@needs_perfetto
def test_serving_engine_produces_scheduled_windows(tmp_path, devices):
    hist = str(tmp_path / "hist")
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    serve = deepspeed_tpu.init_serving(
        model, config={"dtype": "float32", "max_out_tokens": 64,
                       "continuous_profiler": {
                           "enabled": True, "every_steps": 2,
                           "every_seconds": 3600.0, "capture_steps": 1,
                           "max_duty_cycle": 1.0, "history_dir": hist}},
        num_slots=2, prefill_chunk=4, decode_block_tokens=3)
    serve.set_params(params)
    try:
        assert serve._cprof is not None
        # the first CPU window tends to span slot-program compiles (a
        # seconds-long capture), which poisons the measured per-window
        # overhead estimate; the duty-cycle policy has its own dedicated
        # test above, so lift the cap here and test only the cadence
        serve._cprof.max_duty_cycle = 100.0
        ring = serve._cprof.ring
        rng = jax.random.PRNGKey(3)
        waves = 0
        while waves < 6 and len(ring.paths()) < 2:
            keys = jax.random.split(rng, 7)
            rng = keys[0]
            for k in keys[1:]:
                serve.submit(np.asarray(jax.random.randint(k, (5,), 0, 256)),
                             max_new_tokens=12)
            serve.run()
            waves += 1
        wins = ring.latest(4)
        assert len(wins) >= 2, \
            f"{len(wins)} windows after {waves} request waves"
        for w in wins:
            _assert_window_contract(w, "serving")
    finally:
        serve.close()
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("serving", None)


# ---------------------------------------------------------------------------
# readers: /profilez/history + metrics_dump --profile
# ---------------------------------------------------------------------------


def _tools_import(name):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_profilez_history_endpoint_and_dump_profile(tmp_path):
    from deepspeed_tpu.monitor.server import MetricsServer

    hist = str(tmp_path / "hist")
    prof = continuous.ContinuousProfiler(engine="hist-test",
                                         history_dir=hist)
    prof.ring.append(_window())
    prof.ring.append(_window(comm=0.002))
    server = MetricsServer(MetricsRegistry().enable(), port=0).start()
    try:
        with urllib.request.urlopen(f"{server.url}/profilez/history?n=4",
                                    timeout=10) as resp:
            snap = json.load(resp)
        assert "hist-test" in snap["engines"]
        assert [w["seq"] for w in snap["windows"]
                if w["engine"] == "train"] == [1, 2]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{server.url}/profilez/history?n=bogus",
                                   timeout=10)
        assert ei.value.code == 400

        # metrics_dump --profile over BOTH sources: live URL and ring dir
        metrics_dump = _tools_import("metrics_dump")
        for src in (server.url, hist):
            loaded = metrics_dump.load_profile_history(src)
            assert len(loaded["windows"]) == 2
            text = metrics_dump.render_profile(loaded)
            assert "fwd_bwd" in text and "comm_all_gather" in text
            assert "window #2" in text
        rows = metrics_dump.profile_rows(loaded["windows"][-1])
        assert rows[0][0] == "fwd_bwd"      # sorted by share, descending
        shares = [float(r[2].rstrip("%")) for r in rows]
        assert shares == sorted(shares, reverse=True)
    finally:
        server.stop()
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("hist-test", None)


def test_history_snapshot_orders_and_limits(tmp_path):
    prof = continuous.ContinuousProfiler(engine="snap-test",
                                         history_dir=str(tmp_path))
    try:
        for _ in range(3):
            prof.ring.append(_window())
        snap = continuous.history_snapshot(limit=2)
        ours = [w for w in snap["windows"] if w["engine"] == "train"]
        assert [w["seq"] for w in ours] == [2, 3]
    finally:
        with continuous._ACTIVE_LOCK:
            continuous._ACTIVE.pop("snap-test", None)
