"""FLOPS profiler tests (reference: tests/unit/profiling/, SURVEY.md §5.1)."""

import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.profiling import FlopsProfiler, get_model_profile
from tests.unit.simple_model import SimpleModel, random_dataset


def test_get_model_profile_matmul():
    a = jnp.ones((64, 128), jnp.float32)
    b = jnp.ones((128, 256), jnp.float32)
    flops, macs, n_params = get_model_profile(lambda a, b: a @ b, (a, b))
    want = 2 * 64 * 128 * 256
    # XLA cost analysis counts the dot exactly
    assert flops == 0 or abs(flops - want) / want < 0.1, (flops, want)
    assert n_params == a.size + b.size


def test_engine_profile_printed():
    x, y = random_dataset(n=16)
    cfg = {"train_micro_batch_size_per_gpu": 1, "gradient_accumulation_steps": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "flops_profiler": {"enabled": True, "profile_step": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, rng=jax.random.PRNGKey(0))
    assert engine.flops_profiler is not None
    for i in range(3):
        loss = engine.forward((x[:8], y[:8]))
        engine.backward(loss)
        engine.step()
    # the engine printed at profile_step 2 (through the logger); the collected
    # cost data persists — re-render and assert on the content
    assert engine.flops_profiler._cost, "cost analyses should be collected"
    text = engine.flops_profiler.print_model_profile(profile_step=2)
    assert "Flops Profiler" in text
    assert "flops per train step" in text
    assert engine.flops_profiler.get_total_params() > 0
    assert engine.flops_profiler.get_total_flops() > 0


def test_streamed_offload_profile_nonzero(mesh8):
    """The per-layer streamed offload path must still report train-step FLOPs
    (regression: the whole-program fwdbwd probe doesn't exist there)."""
    from deepspeed_tpu.comm.mesh import set_global_mesh
    from deepspeed_tpu.models import causal_lm

    set_global_mesh(mesh8)
    model = causal_lm("llama-tiny", mesh=mesh8, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, max_seq_len=64, remat=False)
    cfg = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 1, "bf16": {"enabled": True},
           "zero_optimization": {"stage": 3,
                                 "offload_optimizer": {"device": "cpu"},
                                 "offload_param": {"device": "cpu"}},
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "flops_profiler": {"enabled": True, "profile_step": 1},
           "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg,
                                               mesh=mesh8,
                                               rng=jax.random.PRNGKey(3))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 256)
    engine.forward((toks, toks))
    engine.step()
    assert engine._streamed is not None
    assert engine.flops_profiler.get_total_flops() > 0


def test_profiler_api_shapes():
    p = FlopsProfiler()
    p.start_profile()
    assert p.get_total_flops() == 0.0
    assert isinstance(p.get_total_duration(), float)
    p.end_profile()
