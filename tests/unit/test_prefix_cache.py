"""Copy-on-write prefix caching (serving/prefix_cache.py + the refcounted
paged pool + the engine's admission match): trie/allocator unit behavior,
greedy-decode PARITY with the cache warm (outputs must be token-identical
to cold runs and to ``generate()``), COW divergence (live requests sharing
cached pages then diverging), eviction-before-preemption ordering, and the
preempt-resume path re-prefilling THROUGH the cache.  The leak probe
(``PagedKVPool.check_no_leak``) runs after every scenario — finish,
eviction, preempt-resume, and ``drain_finished()`` must all keep the
page accounting exact."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
from deepspeed_tpu.serving import PagedKVPool, PrefixCache


@pytest.fixture(autouse=True)
def _no_unknown_finish_reasons():
    """Same tier-1 guard as test_serving: every release path must
    attribute its finish reason."""
    from deepspeed_tpu.monitor.metrics import get_registry

    yield
    c = get_registry().get("ds_serve_finished_total",
                           labels={"reason": "unknown"})
    assert c is None or c.value == 0


# ---------------------------------------------------------------------------
# trie + refcounted-pool units (pure host bookkeeping, no jax)
# ---------------------------------------------------------------------------

def _toks(*vals):
    return np.asarray(vals, np.int32)


def test_trie_match_insert_page_granular():
    pool = PagedKVPool(2, 64, page_tokens=4)
    cache = PrefixCache(pool)
    prompt = np.arange(1, 11, dtype=np.int32)          # 10 tokens, 2.5 pages
    assert cache.match(prompt) == []
    # simulate a finished request: pages 1,2 hold the two FULL pages
    assert pool.ensure(0, 10)
    pages = pool.owned(0)
    added = cache.insert(prompt, pages[:2])
    assert added == 2 and len(cache) == 2
    assert pool.pages_cached == 2
    # full-page match only; a diverging second page stops the walk
    assert cache.match(prompt) == pages[:2]
    assert cache.match(prompt[:7]) == pages[:1]        # 1 full page + tail
    assert cache.match(prompt[:3]) == []               # below one page
    div = prompt.copy()
    div[5] = 99
    assert cache.match(div) == pages[:1]
    # duplicate insert keeps the EXISTING node's page (the newcomer's
    # duplicate page is simply not pinned)
    assert pool.ensure(1, 8)
    dup = pool.owned(1)
    assert cache.insert(prompt, dup[:2]) == 0
    assert cache.match(prompt) == pages[:2]
    pool.release(0)
    pool.release(1)
    # cached pages survive their request's release, off the free list
    assert pool.pages_cached == 2 and pool.pages_free == pool.num_pages - 3
    pool.check_no_leak()


def test_pool_refcounts_adopt_share_release():
    pool = PagedKVPool(3, 64, page_tokens=16)
    assert pool.ensure(0, 48)                          # 3 private pages
    shared = pool.owned(0)
    cache = PrefixCache(pool)
    cache.insert(np.arange(48, dtype=np.int32), shared)
    # slot 1 adopts the cached pages read-only: refcounts go to 2
    pool.adopt(1, shared[:2])
    assert [pool.ref(p) for p in shared] == [2, 2, 1]
    assert (pool.page_table[1, :2] == shared[:2]).all()
    assert pool.pages_used == 3                        # distinct pages
    # slot 1 then grows privately past the shared prefix
    assert pool.ensure(1, 48)
    assert pool.slot_pages_used(1) == 3
    assert pool.page_table[1, 2] not in shared
    pool.check_no_leak()
    # releasing the ORIGINAL owner keeps shared pages alive (ref 1 +
    # cache pin); releasing the adopter parks them as cached-only
    assert pool.release(0) == 0                        # all cached/shared
    assert [pool.ref(p) for p in shared] == [1, 1, 0]
    pool.check_no_leak()
    freed = pool.release(1)
    assert freed == 1                                  # only the private page
    assert pool.pages_cached == 3 and pool.pages_used == 0
    pool.check_no_leak()
    # eviction (LRU) hands cached pages back to the free list
    evicted = 0
    while cache.evict_lru():
        evicted += 1
        pool.check_no_leak()
    assert evicted == 3 and pool.pages_cached == 0
    assert pool.pages_free == pool.num_pages - 1
    pool.check_no_leak()


def test_eviction_lru_order_and_ref_protection():
    pool = PagedKVPool(2, 64, page_tokens=4)
    cache = PrefixCache(pool)
    old = np.arange(100, 108, dtype=np.int32)          # 2 pages
    new = np.arange(200, 208, dtype=np.int32)
    assert pool.ensure(0, 8)
    cache.insert(old, pool.owned(0))
    pool.release(0)
    assert pool.ensure(0, 8)
    cache.insert(new, pool.owned(0))
    pool.release(0)
    new_pages = cache.match(new)                       # touches 'new' (LRU)
    # leaf-first + LRU: 'old' leaf goes before anything of 'new'
    old_pages = cache.match(old)
    _ = cache.match(new)                               # make 'new' freshest
    assert cache.evict_lru() == 1
    assert cache.match(old) == old_pages[:1]           # lost its leaf only
    # a page a live slot references is never evicted: adopt 'new' pages
    pool.adopt(1, new_pages)
    while cache.evict_lru():
        pool.check_no_leak()
    assert cache.match(new) == new_pages               # survived eviction
    assert cache.match(old) == []
    pool.release(1)
    pool.check_no_leak()


# ---------------------------------------------------------------------------
# end-to-end serving parity on the CPU mesh
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def setup(devices):
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "float32", "max_out_tokens": 64})
    ref.set_params(params)
    return model, params, ref


def _serve(model, params, **over):
    cfg = {"dtype": "float32", "max_out_tokens": 64, "kv_page_tokens": 16,
           **over}
    s = deepspeed_tpu.init_serving(model, config=cfg, num_slots=2,
                                   prefill_chunk=8, decode_block_tokens=3)
    s.set_params(params)
    return s


def _ref_out(ref, prompt, n):
    return np.asarray(ref.generate(np.asarray(prompt)[None],
                                   max_new_tokens=n,
                                   do_sample=False))[0, len(prompt):]


def _shared_prefix_prompts(rng, prefix_len=48, tails=(4, 7, 2)):
    keys = jax.random.split(rng, len(tails) + 1)
    prefix = np.asarray(jax.random.randint(keys[0], (prefix_len,), 0, 256))
    prompts = [np.concatenate(
        [prefix, np.asarray(jax.random.randint(k, (t,), 0, 256))])
        for k, t in zip(keys[1:], tails)]
    return prefix, prompts


def test_shared_prefix_parity_and_prefill_savings(setup, rng):
    """The tentpole acceptance shape at tier-1 size: a shared-prefix wave
    through a WARM cache must stay token-identical to generate() while
    computing under 60% of the prefill tokens a cold engine pays (the
    bench trace pins the >= 40% savings at scale; here every follow-up
    request shares a 3-page prefix, so savings are deterministic)."""
    from deepspeed_tpu.monitor.metrics import get_registry

    model, params, ref = setup
    reg = get_registry()
    reg.enable()
    serve = _serve(model, params)
    try:
        prefix, prompts = _shared_prefix_prompts(rng)
        news = [6, 5, 7]
        want = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
        # wave 1: cold — request 0 warms the cache at its finish
        warm = serve.submit(prompts[0], max_new_tokens=news[0])
        serve.run()
        assert warm.prefix_hit_tokens == 0
        np.testing.assert_array_equal(np.asarray(warm.output_tokens), want[0])
        assert serve.prefix_cache is not None and len(serve.prefix_cache) == 3
        serve.pool.check_no_leak()
        # wave 2: every request (including an exact re-ask of prompt 0)
        # shares the cached 48-token prefix
        reg.reset()
        reqs = [serve.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        serve.run()
        for i, (req, w) in enumerate(zip(reqs, want)):
            np.testing.assert_array_equal(
                np.asarray(req.output_tokens), w,
                err_msg=f"request {i} diverged with a warm prefix cache")
        snap = reg.snapshot()
        hit = snap["ds_serve_prefix_hit_tokens_total"]
        miss = snap["ds_serve_prefix_miss_tokens_total"]
        total = sum(len(p) for p in prompts)
        assert hit + miss == total
        # the acceptance floor, deterministically beaten here: 3 x 48
        # shared tokens of 167 total prompt tokens
        assert hit / total >= 0.4, (hit, miss)
        assert snap["ds_serve_prefill_tokens_total"] == miss
        assert all(r.prefix_hit_tokens >= 32 for r in reqs)
        serve.scheduler.drain_finished()
        serve.pool.check_no_leak()
    finally:
        reg.reset()
        reg.disable()
        serve.close()


def test_cow_divergence_two_live_requests(setup, rng):
    """Two LIVE requests adopt the same cached pages (one an exact
    duplicate of the cached prompt — the partial-boundary COW path — one
    diverging mid-prefix) and must both match their cold-run outputs:
    shared pages are read-only, each divergent continuation writes only
    its own private/COW pages."""
    model, params, ref = setup
    serve = _serve(model, params)
    try:
        prefix, prompts = _shared_prefix_prompts(rng, prefix_len=48,
                                                 tails=(6,))
        base = prompts[0]                      # 54 tokens
        fork = base.copy()
        fork[40] = (fork[40] + 1) % 256        # diverges INSIDE page 2
        want_base = _ref_out(ref, base, 8)
        want_fork = _ref_out(ref, fork, 8)
        cow_calls = {"n": 0}
        real_cow = serve._cow_fn()

        def counting_cow(*a):
            cow_calls["n"] += 1
            return real_cow(*a)

        serve._cow_copy = counting_cow
        warm = serve.submit(base, max_new_tokens=8)
        serve.run()
        np.testing.assert_array_equal(np.asarray(warm.output_tokens),
                                      want_base)
        # both live at once (2 slots): the duplicate fully matches the
        # cached pages -> boundary page 3 (rows 48..53) is only partially
        # needed... base re-ask matches 3 full pages = 48 aligned tokens;
        # an exact 48-token prompt would COW.  Drive the COW explicitly:
        exact = serve.submit(prefix, max_new_tokens=8)      # prompt == cache
        forked = serve.submit(fork, max_new_tokens=8)
        serve.run()
        want_exact = _ref_out(ref, prefix, 8)
        np.testing.assert_array_equal(
            np.asarray(exact.output_tokens), want_exact,
            err_msg="exact-duplicate prompt diverged through the COW page")
        np.testing.assert_array_equal(
            np.asarray(forked.output_tokens), want_fork,
            err_msg="mid-prefix fork diverged over shared pages")
        # the exact duplicate matched 47 of its 48 tokens: pages 0,1
        # shared outright, page 2 copy-on-written (one device page copy)
        assert exact.prefix_hit_tokens == 47
        assert cow_calls["n"] >= 1, "exact-duplicate admission must COW"
        # the fork matched the aligned 2-page prefix only
        assert forked.prefix_hit_tokens == 32
        serve.scheduler.drain_finished()
        serve.pool.check_no_leak()
    finally:
        serve.close()


def test_eviction_before_preemption(setup, rng):
    """Pool pressure must reclaim refcount-0 cached pages (LRU) BEFORE
    any live request is preempted: a pool whose free list is exhausted by
    cached history serves a fresh 2-request wave with evictions and ZERO
    preemptions."""
    from deepspeed_tpu.monitor.metrics import get_registry

    model, params, ref = setup
    reg = get_registry()
    reg.enable()
    # 6 usable pages; two 3-page requests fit EXACTLY with nothing spare
    serve = _serve(model, params, kv_pool_tokens=96)
    try:
        assert serve.pool.num_pages == 7
        k1, k2, k3 = jax.random.split(rng, 3)
        warm_p = np.asarray(jax.random.randint(k1, (37,), 0, 256))
        warm = serve.submit(warm_p, max_new_tokens=4)    # 3 pages, 2 cached
        serve.run()
        assert warm.done and serve.pool.pages_cached == 2
        reg.reset()
        prompts = [np.asarray(jax.random.randint(k, (24,), 0, 256))
                   for k in (k2, k3)]
        want = [_ref_out(ref, p, 20) for p in prompts]   # pos -> 44: 3 pages
        reqs = [serve.submit(p, max_new_tokens=20) for p in prompts]
        serve.run()
        snap = reg.snapshot()
        assert snap["ds_serve_prefix_evictions_total"] == 2, \
            "cached pages must be evicted under pool pressure"
        assert snap.get("ds_serve_preempted_total", 0) == 0, \
            "eviction must satisfy pressure BEFORE preempting live slots"
        assert sum(r.preemptions for r in reqs) == 0
        for req, w in zip(reqs, want):
            np.testing.assert_array_equal(np.asarray(req.output_tokens), w)
        serve.scheduler.drain_finished()
        serve.pool.check_no_leak()
    finally:
        reg.reset()
        reg.disable()
        serve.close()


def test_preempt_resume_re_prefills_through_cache(setup, rng):
    """LIFO preemption gets cheaper: the victim's prompt pages are
    inserted into the cache at preempt time, so its requeue-front resume
    re-prefills through the cache — prefill tokens are SAVED on resume
    (asserted), and the continuation stays token-identical."""
    model, params, ref = setup
    serve = _serve(model, params, kv_pool_tokens=80)     # 5 usable pages
    try:
        assert serve.pool.num_pages == 6
        k1, k2 = jax.random.split(rng)
        prompts = [np.asarray(jax.random.randint(k1, (18,), 0, 256)),
                   np.asarray(jax.random.randint(k2, (19,), 0, 256))]
        want = [_ref_out(ref, p, 30) for p in prompts]   # pos -> 48/49
        reqs = [serve.submit(p, max_new_tokens=30) for p in prompts]
        serve.run()
        assert sum(r.preemptions for r in reqs) >= 1, \
            "5-page pool serving two 3-page requests must preempt"
        victims = [r for r in reqs if r.preemptions]
        # the resume matched the victim's own just-cached prompt page(s):
        # at least one full prompt page (16 tokens) was NOT recomputed
        assert all(v.prefix_hit_tokens >= 16 for v in victims), \
            [v.prefix_hit_tokens for v in victims]
        for i, (req, w) in enumerate(zip(reqs, want)):
            np.testing.assert_array_equal(
                np.asarray(req.output_tokens), w,
                err_msg=f"request {i} diverged across preempt-resume "
                        f"through the prefix cache")
        serve.scheduler.drain_finished()
        serve.pool.check_no_leak()
    finally:
        serve.close()


def test_prefix_cache_off_and_fixed_slot_unaffected(setup, rng):
    """``prefix_caching=False`` serves token-identically with zero cache
    state; the fixed-slot layout never builds a cache at all."""
    model, params, ref = setup
    prefix, prompts = _shared_prefix_prompts(rng, tails=(5, 3))
    news = [5, 4]
    want = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
    off = _serve(model, params, prefix_caching=False)
    try:
        assert off.prefix_cache is None
        for _ in range(2):                      # repeat wave: nothing cached
            reqs = [off.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, news)]
            off.run()
            for req, w in zip(reqs, want):
                np.testing.assert_array_equal(
                    np.asarray(req.output_tokens), w)
                assert req.prefix_hit_tokens == 0
        off.pool.check_no_leak()
    finally:
        off.close()
    fixed = _serve(model, params, paged_kv_cache=False)
    try:
        assert fixed.prefix_cache is None and fixed.pool is None
    finally:
        fixed.close()


@pytest.mark.parametrize("position,fused", [("learned", False),
                                            ("rope", False),
                                            ("alibi", True)])
def test_warm_cache_parity_other_paths(devices, rng, position, fused):
    """Cache-on-vs-off token identity must hold for every position
    scheme AND both decode implementations (the adopted pages' KV is
    position-absolute, so rope/learned/alibi all reuse it exactly; the
    fused Pallas kernel and the unfused gather path both read shared
    pages through the same page-table indirection)."""
    mesh = build_mesh(fsdp=8, devices=devices)
    set_global_mesh(mesh)
    model = causal_lm("llama-tiny", mesh=mesh, num_layers=2, hidden_size=64,
                      intermediate_size=128, num_heads=4, num_kv_heads=2,
                      vocab_size=256, remat=False, position=position,
                      max_seq_len=64)
    prefix, prompts = _shared_prefix_prompts(rng, prefix_len=32,
                                             tails=(5, 9))
    news = [6, 4]
    params = model.init(rng, jnp.asarray(prompts[0])[None])
    cfg = {"dtype": "float32", "max_out_tokens": 64,
           "use_fused_decode": fused, "kv_page_tokens": 16}
    ref = deepspeed_tpu.init_inference(model, config=cfg)
    ref.set_params(params)
    want = [_ref_out(ref, p, n) for p, n in zip(prompts, news)]
    serve = deepspeed_tpu.init_serving(model, config=cfg, num_slots=2,
                                       prefill_chunk=8,
                                       decode_block_tokens=3)
    serve.set_params(params)
    assert (serve.engine._dparams is not None) == fused
    try:
        # wave 1 warms the cache; wave 2 serves the same prompts hot
        for wave in range(2):
            reqs = [serve.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, news)]
            serve.run()
            for i, (req, w) in enumerate(zip(reqs, want)):
                np.testing.assert_array_equal(
                    np.asarray(req.output_tokens), w,
                    err_msg=f"{position}/fused={fused} request {i} "
                            f"wave {wave}")
            if wave:
                assert all(r.prefix_hit_tokens >= 16 for r in reqs)
            serve.scheduler.drain_finished()
            serve.pool.check_no_leak()
    finally:
        serve.close()
