"""Elastic agent v2 e2e (VERDICT r3 item 9): 2 processes train with
checkpointing, one is killed mid-run, the agent validates the surviving
world against the elastic config and restarts it, and training resumes from
the latest checkpoint and completes.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.elasticity import DSElasticAgent

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


ELASTIC_SECTION = {
    "enabled": True,
    "max_train_batch_size": 4,
    "micro_batch_sizes": [1, 2, 4],
    "min_gpus": 1,
    "max_gpus": 2,
    "version": 0.1,
}


def test_validate_world_rejects_outside_set(tmp_path):
    agent = DSElasticAgent({"elasticity": dict(ELASTIC_SECTION, max_gpus=2)},
                           "unused.py", num_procs=2)
    assert agent._validate_world(2) in (1, 2, 4)
    assert agent._validate_world(1) in (1, 2, 4)
    from deepspeed_tpu.elasticity import ElasticityIncompatibleWorldSize

    with pytest.raises(ElasticityIncompatibleWorldSize):
        agent._validate_world(3)


def test_world_probe_validates_and_falls_back(tmp_path):
    """The ``--world-size-file`` probe: missing/garbage files keep the
    default, readings clamp to num_procs, and an elastic-invalid reading
    is rejected at relaunch (unit-level)."""
    path = tmp_path / "world"
    agent = DSElasticAgent({"elasticity": ELASTIC_SECTION}, "unused.py",
                           num_procs=2,
                           world_size_fn=DSElasticAgent.world_size_file_fn(
                               str(path)))
    assert agent._probe_world(2) == 2          # no file: default
    path.write_text("not a number")
    assert agent._probe_world(2) == 2
    path.write_text("1")
    assert agent._probe_world(2) == 1          # shrink reading
    path.write_text("64")
    assert agent._probe_world(1) == 2          # clamped to num_procs
    path.write_text("0")
    assert agent._probe_world(2) == 2          # nonsense: default


def test_world_size_file_grows_next_incarnation(tmp_path):
    """Changed-device-set detection ACROSS a restart: the agent starts at
    the probed world 1 (capacity reported down), the incarnation crashes
    after flipping the probe file to 2 (capacity back), and the agent
    GROWS the relaunch to world 2 instead of relaunching the survivor
    count.  Stdlib-only child: the grow path is agent logic, not jax."""
    world_file = tmp_path / "world"
    world_file.write_text("1")
    marker = tmp_path / "incarnations.txt"
    script = tmp_path / "stub.py"
    script.write_text(textwrap.dedent("""\
        import os, sys
        marker, world_file = sys.argv[1], sys.argv[2]
        restart = int(os.environ["DS_ELASTIC_RESTART"])
        world = int(os.environ["WORLD_SIZE"])
        rank = int(os.environ["RANK"])
        with open(marker, "a") as fh:
            fh.write(f"{restart}:{world}:{rank}\\n")
        if restart == 0:
            # "the preempted hosts came back": flip the availability file
            # the scheduler keeps current, then die as a member loss
            with open(world_file, "w") as fh:
                fh.write("2")
            sys.exit(1)
        sys.exit(0)
        """))
    agent = DSElasticAgent(
        {"elasticity": ELASTIC_SECTION}, str(script),
        user_args=[str(marker), str(world_file)], num_procs=2,
        max_restarts=3, no_local_rank=True,
        world_size_fn=DSElasticAgent.world_size_file_fn(str(world_file)))
    assert agent.run() == 0
    lines = marker.read_text().strip().splitlines()
    by_restart = {}
    for line in lines:
        r, w, rank = map(int, line.split(":"))
        by_restart.setdefault(r, []).append((w, rank))
    # incarnation 0 ran at the probed world 1; incarnation 1 GREW to 2
    assert by_restart[0] == [(1, 0)], by_restart
    assert sorted(by_restart[1]) == [(2, 0), (2, 1)], by_restart
    assert agent.restart_count == 1


def test_kill_one_member_restart_resumes(tmp_path):
    """The done-criterion: rank 1 dies at step 2 of 4; the agent restarts at
    world=1; the survivor resumes from the step-2 checkpoint and finishes."""
    ckdir = tmp_path / "ck"
    ckdir.mkdir()
    cfg_path = tmp_path / "ds_config.json"
    cfg_path.write_text(json.dumps({"elasticity": ELASTIC_SECTION}))
    script = tmp_path / "train_stub.py"
    script.write_text(textwrap.dedent("""\
        import json, os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["DS_ACCELERATOR"] = "cpu"
        os.environ.pop("XLA_FLAGS", None)
        sys.path.insert(0, %r)
        import jax
        from deepspeed_tpu import comm
        comm.init_distributed()
        import deepspeed_tpu
        from tests.unit.simple_model import SimpleModel, random_dataset

        world = int(os.environ["WORLD_SIZE"])
        restart = int(os.environ["DS_ELASTIC_RESTART"])
        ckdir = %r
        total_steps = 4
        # elastic invariant: global batch 4 at any world size
        cfg = {"train_batch_size": 4,
               "train_micro_batch_size_per_gpu": 4 // world,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "steps_per_print": 10**9}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=8), config=cfg,
            rng=jax.random.PRNGKey(0))
        x, y = random_dataset(n=8, seed=3)
        engine.forward((x[:4], y[:4]))  # init state before any load
        engine.step()
        start = 1
        loaded, _ = engine.load_checkpoint(ckdir)
        if loaded:
            start = int(os.path.basename(loaded).replace("global_step", "")) + 1
        for step in range(start, total_steps + 1):
            engine.forward((x[:4], y[:4]))
            engine.step()
            engine.save_checkpoint(ckdir, tag=f"global_step{step}")
            comm.barrier()
            if restart == 0 and step == 2 and os.environ["RANK"] == "1":
                os._exit(1)  # simulated member loss
        if os.environ["RANK"] == "0":
            with open(os.path.join(ckdir, "done.json"), "w") as fh:
                json.dump({"restart": restart, "resumed_from": start,
                           "world": world}, fh)
        print("STUB DONE", os.environ["RANK"])
        """) % (REPO, str(ckdir)))

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
           and not k.startswith(("PALLAS_AXON", "AXON_"))}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.elasticity.elastic_agent",
         "--ds_config", str(cfg_path), "--num_procs", "2",
         "--master_port", str(_free_port()), "--no_local_rank", str(script)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    with open(ckdir / "done.json") as fh:
        done = json.load(fh)
    # the surviving incarnation: restarted once, world shrank to 1, resumed
    # from the step-2 checkpoint (not from scratch)
    assert done["restart"] == 1, done
    assert done["world"] == 1, done
    assert done["resumed_from"] == 3, done
    assert "restart #1 at world=1" in proc.stderr + proc.stdout
