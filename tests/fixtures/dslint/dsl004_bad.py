"""Seeded DSL004 violation: a metric literal outside the ``ds_``
namespace, born behind a branch the runtime guard may never execute.
Parsed by the analyzer only — never imported or executed."""

from deepspeed_tpu.monitor.metrics import get_registry


def register(flag):
    reg = get_registry()
    if flag:   # rarely-taken branch: the runtime guard never sees it
        return reg.counter("serve_shadow_requests_total", "no ds_ prefix")
    return reg.gauge("ds_serve_documented_ok", "fine if documented")
