"""Seeded DSL003 violation tree: a 'jax-free' tool whose closure reaches
jax through a helper that imports the package the normal way (the
fleet_dump incident, PR 7).  Parsed by the analyzer only."""

import helper  # noqa: F401
