from deepspeed_tpu.monitor import metrics  # noqa: F401  <- pulls __init__
