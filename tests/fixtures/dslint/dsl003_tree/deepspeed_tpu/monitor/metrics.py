import json  # noqa: F401  (stdlib-only leaf)
