import jax  # noqa: F401  (the package init every normal import executes)
