"""Seeded DSL005 violations (lives under a ``comm/`` path on purpose —
the rule scopes to collective-wrapper directories): a bare collective
with no ``ds_comm_`` scope, and a scope nested inside a telemetry
conditional (the PR 3 compiled-program-stability contract).  Parsed by
the analyzer only — never imported or executed."""

from jax import lax

from deepspeed_tpu.profiling.trace import scope as _scope


def all_reduce(x, axis):
    return lax.psum(x, axis)                       # <- DSL005 (no scope)


def all_gather(x, axis, registry):
    if registry.enabled:
        with _scope("ds_comm_all_gather"):         # <- DSL005 (conditional)
            return lax.all_gather(x, axis, axis=0, tiled=True)
    return lax.all_gather(x, axis, axis=0, tiled=True)
