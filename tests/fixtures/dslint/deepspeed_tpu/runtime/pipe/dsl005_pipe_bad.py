"""Seeded DSL005 violations for the PIPELINE boundary form (lives under
a ``runtime/pipe/`` path on purpose — ISSUE 16 extended the rule to the
schedules that dispatch their stage-boundary rings directly): a bare
boundary ``ppermute`` with no ``ds_comm_`` scope, and a ring hop whose
scope hides inside a telemetry conditional.  Parsed by the analyzer
only — never imported or executed."""

from jax import lax

from deepspeed_tpu.profiling.trace import scope as _scope


def boundary_send(x, axis, perm):
    return lax.ppermute(x, axis, perm)           # <- DSL005 (no scope)


def boundary_send_recorded(x, axis, perm, comm_metrics):
    if comm_metrics.enabled:
        with _scope("ds_comm_ppermute"):         # <- DSL005 (conditional)
            return lax.ppermute(x, axis, perm)
    return lax.ppermute(x, axis, perm)
