"""Clean twin of dsl005_pipe_bad.py: the pipeline boundary idiom the
rule enforces — the byte RECORD may be conditional, the ring hop and
its ``ds_comm_ppermute`` scope are not (compiled-program stability:
toggling telemetry never changes the traced program)."""

from jax import lax

from deepspeed_tpu.profiling.trace import scope as _scope


def boundary_send(x, axis, perm, comm_metrics):
    if comm_metrics.enabled:
        comm_metrics.record("ppermute", axis, x)
    with _scope("ds_comm_ppermute"):
        return lax.ppermute(x, axis, perm)
