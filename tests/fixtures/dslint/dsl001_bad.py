"""Seeded DSL001 violation: a raw ``jax.device_put`` result reaching a
``donate_argnums`` callee (the PR 2/4/10 corruption class).  Parsed by
the analyzer only — never imported or executed."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def accum(state, batch):
    return state + batch


def step(state, host_grads, shardings):
    g = jax.device_put(host_grads, shardings)   # numpy-aliased on CPU
    return accum(g, 1.0)                        # donated arg 0  <- DSL001


def commit(self, compute):
    new_params = jax.device_put(compute, self._shardings)
    # the engine-state sink: these leaves are donated next dispatch
    self.state = self._state._replace(params=new_params)   # <- DSL001
