"""Seeded DSL002 violations: device syncs on a hot path, including one
hiding in the telemetry-DISABLED branch (the PR 3/7 class).  Parsed by
the analyzer only — never imported or executed."""

import numpy as np


class Engine:
    def _decode_block(self):   # dslint: hot
        toks = self._dispatch()
        if not self.registry.enabled:
            # this branch only runs with metrics OFF — no test times it
            self._last = float(toks.sum())              # <- DSL002
        vals = np.asarray(toks)                         # <- DSL002
        got = toks.item()                               # <- DSL002
        return vals, got
