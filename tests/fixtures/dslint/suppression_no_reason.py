"""Seeded DSL000 case: a suppression WITHOUT the required ``-- reason``
tail neither suppresses the finding nor passes itself.  Parsed by the
analyzer only — never imported or executed."""

import numpy as np


class Engine:
    def _drain_one(self):   # dslint: hot
        toks = self._fetch()
        return np.asarray(toks)  # dslint: disable=DSL002
