"""Clean twin: hot-path code that honors every rule — zero findings.
Parsed by the analyzer only — never imported or executed."""

import functools
import time

import jax

from engine_seams import _owned_device_put


@functools.partial(jax.jit, donate_argnums=(0,))
def accum(state, batch):
    return state + batch


def step(state, host_grads, shardings):
    g = _owned_device_put(host_grads, shardings)     # owned copy seam
    return accum(g, 1.0)


class Engine:
    _dslint_shared = {"_ring": "atomic", "_anchor": "swap"}

    def __init__(self):
        self._ring = []
        self._anchor = {"perf": 0.0}

    def _decode_block(self):   # dslint: hot
        toks = self._dispatch()
        t0 = time.perf_counter()
        if self.registry.enabled:
            self._m.record(float(toks[0]))           # enabled-only branch
        self._ring.append({"t": t0})                 # GIL-atomic append
        self._anchor = {"perf": t0}                  # whole-object swap
        return toks
