"""Seeded DSL006 violations: tagged shared structures mutated outside
their declared discipline (the PR 7 scrape-race class).  Parsed by the
analyzer only — never imported or executed."""

import time


class Tracer:
    _dslint_shared = {"_ring": "atomic", "_anchor": "swap",
                      "_pending": "lock:_lock"}

    def __init__(self):
        self._ring = []
        self._anchor = {"perf": 0.0}
        self._pending = None

    def record(self, rec):
        self._ring.append(rec)                  # atomic op: fine
        self._ring[0]["t"] = time.time()        # <- DSL006 (published rec)
        self._anchor["perf"] = time.time()      # <- DSL006 (torn anchor)
        self._pending = rec                     # <- DSL006 (lock not held)
