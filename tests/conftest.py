"""Test harness configuration.

The reference tests distributed behavior by forking N processes on one box
(SURVEY.md §4 ``DistributedTest``).  The TPU-native equivalent is simpler and
stronger: a single process with N virtual XLA CPU devices, so every test runs
the real SPMD code path (mesh + collectives) deterministically.  This must run
before jax is imported anywhere.
"""

import os

# Force-override: the session environment pins JAX_PLATFORMS to the TPU tunnel;
# tests always run on the virtual CPU mesh (set DSTPU_TEST_ON_TPU=1 to opt out).
if not os.environ.get("DSTPU_TEST_ON_TPU"):
    # The concurrency-optimized scheduler can order two independent
    # collectives differently across the in-process CPU "devices", deadlocking
    # the rendezvous (observed with MoE's ep all-gathers + loss all-reduce).
    # TPU executes collectives in one serialized stream, so this is test-only.
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               "--xla_cpu_enable_concurrency_optimized_scheduler=false "
                               + os.environ.get("XLA_FLAGS", ""))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DS_ACCELERATOR"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

# jax-version shims (jax.shard_map on jax <= 0.4.x) BEFORE any test module
# does `from jax import shard_map`
from deepspeed_tpu.utils.compat import install_jax_compat  # noqa: E402

install_jax_compat()

# Persistent XLA compilation cache: the suite compiles many IDENTICAL
# tiny-model programs (every engine instance re-jits the same decode loop /
# prefill shapes), and compiles dominate tier-1 wall time on small hosts.
# The cache dedupes by HLO hash within a run and persists across runs.
if not os.environ.get("DSTPU_TEST_ON_TPU"):
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("DSTPU_XLA_CACHE_DIR",
                                         "/tmp/dstpu_xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # older jax without the persistent cache: no-op
        pass


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; register the marker so slow-marked
    # benches (tests/perf/test_serving_bench.py) don't warn
    config.addinivalue_line("markers",
                            "slow: long benchmark; excluded from tier-1")

if not os.environ.get("DSTPU_TEST_ON_TPU"):
    # jax may already be imported by the interpreter's sitecustomize (with
    # JAX_PLATFORMS pinned to the TPU tunnel); the backend is not yet
    # initialized at conftest time, so this still takes effect.
    jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _restore_global_mesh():
    """Tests that set_global_mesh (sp/pp/ep layouts) must not leak their
    mesh into later tests that build engines off the global default."""
    from deepspeed_tpu.comm import mesh as mesh_mod

    prev = mesh_mod._GLOBAL_MESH
    yield
    mesh_mod._GLOBAL_MESH = prev


@pytest.fixture(autouse=True)
def _restore_metrics_registry_enabled():
    """The disabled-by-default metrics registry is process-global, and an
    engine built with ``comms_logger.enabled`` flips it on (PR 3) — a test
    doing so must not leave later tests recording into shared counters
    (the serving suite's unknown-finish-reason guard depends on a clean
    enabled-state baseline)."""
    from deepspeed_tpu.monitor.comms import comm_metrics
    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.request_trace import get_request_tracer

    reg = get_registry()
    tracer = get_request_tracer()
    prev_reg, prev_comms = reg.enabled, comm_metrics.enabled
    prev_tracer = tracer.enabled
    yield
    reg._enabled = prev_reg
    comm_metrics.enabled = prev_comms
    tracer.enabled = prev_tracer


@pytest.fixture(autouse=True)
def _goodput_ledger_guard():
    """A test that leaves the process-global goodput ledger enabled must
    leave it TELESCOPING (category sum == wall at rel 1e-9, the ISSUE 18
    run-attribution contract) — checked after EVERY test, then the
    ledger is reset so run clocks and jsonl paths don't leak across
    tests (the engine enables it from config/env; a leaked enable would
    time unrelated tests into one run)."""
    yield
    from deepspeed_tpu.monitor import goodput_core
    from deepspeed_tpu.monitor.goodput import get_goodput_ledger

    gp = get_goodput_ledger()
    if gp.enabled:
        snap = gp.snapshot()
        gp._path = None          # teardown must not append to a test's jsonl
        gp.disable()
        assert goodput_core.telescopes(snap), (
            "goodput ledger left non-telescoping: wall "
            f"{snap['wall_s']} vs sum {sum(snap['categories'].values())} "
            f"(open regions: {snap['open_regions']})")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8(devices):
    from deepspeed_tpu.comm.mesh import build_mesh

    return build_mesh(fsdp=8, devices=devices)


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)
