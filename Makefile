# Single-command entries the builder's verify recipe runs before the
# suite (see ROADMAP.md for the canonical tier-1 line).

.PHONY: lint lint-json tier1 chaos perf-diff profile-report

# dslint: AST-level invariant checker (docs/LINT.md) — no jax needed
lint:
	python tools/dslint.py deepspeed_tpu tools bench.py

lint-json:
	python tools/dslint.py --json deepspeed_tpu tools bench.py

# perf regression gate over the committed BENCH_*/MULTICHIP_* ledgers
# (tools/perf_ledger.py --check exits 1 when the trajectory tip regresses
# beyond tolerance; no jax needed)
perf-diff:
	python tools/perf_ledger.py --check --all

# newest continuous-profiler window + window-over-window regression
# verdict from the on-disk history ring (docs/OBSERVABILITY.md
# "Continuous profiling"; no jax needed — the ring is plain JSON)
profile-report:
	python tools/trace_report.py --history profile_history

# lint first (seconds), then the tier-1 suite (minutes)
tier1: lint
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider \
		-p no:xdist -p no:randomly

# the slow-marked chaos suites (outside tier-1): the serving fleet
# matrix + bench_fleet_chaos, and the TRAINING matrix
# (tests/perf/test_train_chaos.py — randomized kill-sweep across an
# elastic 4->2->8->4 cycle, multi-round gradient bombs, and the
# bench_elastic_resume rung) at CPU smoke scale
chaos:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m slow -k chaos \
		--continue-on-collection-errors -p no:cacheprovider \
		-p no:xdist -p no:randomly
